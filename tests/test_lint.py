"""Acceptance suite for the static-analysis layer (ISSUE 6).

Four contracts:

- each rule fires on a minimal positive fixture and stays silent on the
  matching negative (the taint machinery's precision is pinned too —
  static args threaded positionally must not poison helpers);
- the runtime OrderedLock catches a seeded lock-order inversion
  deterministically (no deadlock interleaving needed) and tolerates the
  legal patterns (nesting in one consistent order, RLock re-entry);
- the CLI honors the exit-code contract: 0 clean, 1 findings, 2 usage
  error; baselines match on (rule, file, message), absorb at most
  `count` occurrences, and --fix-baseline round-trips;
- THE SELF-CHECK: the full suite over the shipped tpu_ir/ package with
  the checked-in lint_baseline.json yields zero un-baselined findings —
  the analyzers gate the codebase that ships them, so re-introducing
  any hazard this PR fixed (a lock held across a device dispatch, an
  undeclared counter/env read) fails tier-1 with the rule id.
"""

from __future__ import annotations

import json
import textwrap
import threading
from pathlib import Path

import pytest

import tpu_ir
from tpu_ir.cli import main as cli_main
from tpu_ir.lint import (
    Baseline,
    Finding,
    LockOrderInversion,
    OrderedLock,
    PackageIndex,
    run_lint,
)
from tpu_ir.lint.ordered_lock import _OrderGraph

REPO = Path(tpu_ir.__file__).parent.parent


# ---------------------------------------------------------------------------
# fixture-package harness
# ---------------------------------------------------------------------------


def lint_src(tmp_path, source: str, *, name: str = "mod.py",
             families=("jit", "concurrency", "contracts")):
    pkg = tmp_path / "fixpkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    (pkg / name).write_text(textwrap.dedent(source))
    return run_lint(str(pkg), pkg_name="fixpkg", rel_root=str(tmp_path),
                    families=families)


def rules_of(findings) -> set:
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# TPU1xx: jit hazards
# ---------------------------------------------------------------------------


def test_tpu101_host_sync_in_jitted_function(tmp_path):
    fs = lint_src(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def bad(x):
            return float(np.asarray(x).sum())

        def fine(x):
            return float(np.asarray(x).sum())   # host code: allowed
    """)
    hits = [f for f in fs if f.rule == "TPU101"]
    assert hits and all("bad" in f.message for f in hits)


def test_tpu101_item_and_wrapper_assignment_roots(tmp_path):
    # jit roots created by `name = jax.jit(fn)` wrapper assignment are
    # covered, and helpers they call are in the reachable closure
    fs = lint_src(tmp_path, """
        import jax
        import jax.numpy as jnp

        def helper(x):
            return x.item()

        def kernel(x):
            return helper(x) + 1

        kernel_jit = jax.jit(kernel)
    """)
    assert any(f.rule == "TPU101" and ".item()" in f.message for f in fs)


def test_tpu101_numpy_utilities_allowed(tmp_path):
    fs = lint_src(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def ok(x):
            return x.astype(np.dtype(np.int32))
    """)
    assert not [f for f in fs if f.rule == "TPU101"]


def test_tpu102_branch_on_tracer(tmp_path):
    fs = lint_src(tmp_path, """
        import jax

        @jax.jit
        def bad(x):
            if x > 0:
                return x
            return -x
    """)
    assert any(f.rule == "TPU102" and "'x'" in f.message for f in fs)


def test_tpu102_static_and_shape_branches_allowed(tmp_path):
    fs = lint_src(tmp_path, """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("flag", "k"))
        def ok(x, mask, *, flag, k):
            if flag:                      # static argument
                x = x + 1
            if x.shape[0] > k:            # shapes are static
                x = x * 2
            if mask is not None:          # identity test is static
                x = x + mask
            return x
    """)
    assert not [f for f in fs if f.rule == "TPU102"]


def test_tpu102_taint_does_not_leak_through_static_positional_args(
        tmp_path):
    # the regression the first self-run caught: a helper receiving a
    # STATIC value positionally (compat_int_idf / k) must not have that
    # param treated as traced
    fs = lint_src(tmp_path, """
        import jax
        from functools import partial

        def weights(df, compat):
            if compat:                    # static at every call site
                return df * 2
            return df * 3

        @partial(jax.jit, static_argnames=("compat",))
        def kernel(df, *, compat):
            return weights(df, compat)
    """)
    assert not [f for f in fs if f.rule == "TPU102"]


def test_tpu102_taint_flows_through_locals_and_helpers(tmp_path):
    # idf = helper(traced) is traced; branching on it in a second helper
    # that receives it positionally must fire
    fs = lint_src(tmp_path, """
        import jax
        import jax.numpy as jnp

        def shift(w):
            while w > 0:
                w = w - 1
            return w

        @jax.jit
        def kernel(df):
            idf = jnp.log(df)
            return shift(idf)
    """)
    assert any(f.rule == "TPU102" and "while" in f.message for f in fs)


def test_tpu103_print_and_fstring_on_tracer(tmp_path):
    fs = lint_src(tmp_path, """
        import jax

        @jax.jit
        def chatty(x):
            print("score:", x)
            label = f"got {x}"
            return x
    """)
    msgs = [f.message for f in fs if f.rule == "TPU103"]
    assert any("print" in m for m in msgs)
    assert any("f-string" in m for m in msgs)


def test_tpu104_missing_donation(tmp_path):
    fs = lint_src(tmp_path, """
        import jax
        from functools import partial

        @jax.jit
        def bad(buf, chunk, off):
            return jax.lax.dynamic_update_slice(buf, chunk, (off,))

        @partial(jax.jit, donate_argnums=0)
        def good(buf, chunk, off):
            return jax.lax.dynamic_update_slice(buf, chunk, (off,))

        @jax.jit
        def fresh(chunk):
            buf = jax.numpy.zeros(8)      # local buffer: nothing to donate
            return jax.lax.dynamic_update_slice(buf, chunk, (0,))
    """)
    hits = [f for f in fs if f.rule == "TPU104"]
    assert len(hits) == 1 and "bad" in hits[0].message


# ---------------------------------------------------------------------------
# TPU2xx: concurrency
# ---------------------------------------------------------------------------


def test_tpu201_lock_order_cycle(tmp_path):
    fs = lint_src(tmp_path, """
        import threading

        _a = threading.Lock()
        _b = threading.Lock()

        def one():
            with _a:
                with _b:
                    pass

        def two():
            with _b:
                with _a:
                    pass
    """)
    hits = [f for f in fs if f.rule == "TPU201"]
    assert hits and "cycle" in hits[0].message


def test_tpu201_consistent_order_is_clean(tmp_path):
    fs = lint_src(tmp_path, """
        import threading

        _a = threading.Lock()
        _b = threading.Lock()

        def one():
            with _a:
                with _b:
                    pass

        def two():
            with _a:
                with _b:
                    pass
    """)
    assert not [f for f in fs if f.rule == "TPU201"]


def test_tpu202_lock_across_device_dispatch(tmp_path):
    # the shape of the scorer bug this PR fixed: lazy init dispatching
    # device work under the lock — including through a helper call
    fs = lint_src(tmp_path, """
        import threading
        import jax.numpy as jnp

        def upload(x):
            return jnp.asarray(x)

        class Lazy:
            def __init__(self):
                self._lock = threading.Lock()
                self._val = None

            def get_direct(self, x):
                with self._lock:
                    if self._val is None:
                        self._val = jnp.asarray(x)
                return self._val

            def get_via_helper(self, x):
                with self._lock:
                    if self._val is None:
                        self._val = upload(x)
                return self._val

            def get_fixed(self, x):
                val = jnp.asarray(x)
                with self._lock:
                    if self._val is None:
                        self._val = val
                return self._val
    """)
    hits = [f for f in fs if f.rule == "TPU202"]
    assert len(hits) == 2
    assert {("get_direct" in f.message, "get_via_helper" in f.message)
            for f in hits} == {(True, False), (False, True)}


def test_tpu203_lock_across_file_io(tmp_path):
    fs = lint_src(tmp_path, """
        import threading

        _lock = threading.Lock()

        def save(path, data):
            with _lock:
                with open(path, "w") as f:
                    f.write(data)

        def fine(path, data):
            blob = data.encode()
            with _lock:
                pass
    """)
    hits = [f for f in fs if f.rule == "TPU203"]
    assert len(hits) == 1 and "save" in hits[0].message


def test_tpu204_directly_nested_same_lock(tmp_path):
    # the blatant form: `with lock:` nested straight inside `with lock:`
    # (deadlocks on first execution) must fire without any helper call
    fs = lint_src(tmp_path, """
        import threading

        _lock = threading.Lock()
        _rlock = threading.RLock()

        def bad():
            with _lock:
                with _lock:
                    pass

        def fine():
            with _rlock:
                with _rlock:
                    pass
    """)
    hits = [f for f in fs if f.rule == "TPU204"]
    assert len(hits) == 1 and "bad" in hits[0].message


def test_tpu202_through_call_cycle(tmp_path):
    # mutual recursion f<->g where f does the IO: the effect summary of
    # g computed mid-cycle must not be memoized incomplete — a caller
    # holding a lock across g must still see the transitive open()
    fs = lint_src(tmp_path, """
        import threading

        _lock = threading.Lock()

        def f(path, depth):
            if depth > 0:
                return g(path, depth - 1)
            with open(path) as fh:
                return fh.read()

        def g(path, depth):
            return f(path, depth)

        def locked_read(path):
            with _lock:
                return g(path, 1)
    """)
    hits = [f for f in fs if f.rule == "TPU203"]
    assert len(hits) == 1 and "locked_read" in hits[0].message


def test_tpu204_self_deadlock_through_helper(tmp_path):
    fs = lint_src(tmp_path, """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self.n += 1

            def bump_twice(self):
                with self._lock:
                    self.bump()       # re-acquires the non-reentrant lock
    """)
    hits = [f for f in fs if f.rule == "TPU204"]
    assert len(hits) == 1 and "bump_twice" in hits[0].message


def test_rlock_reentry_not_flagged(tmp_path):
    fs = lint_src(tmp_path, """
        import threading

        class Box:
            _lock = threading.RLock()

            def inner(self):
                with self._lock:
                    return 1

            def outer(self):
                with self._lock:
                    return self.inner()
    """)
    assert not [f for f in fs if f.rule in ("TPU201", "TPU204")]


# ---------------------------------------------------------------------------
# TPU3xx: contracts
# ---------------------------------------------------------------------------


def test_tpu301_raw_env_read(tmp_path):
    fs = lint_src(tmp_path, """
        import os

        def knob():
            return os.environ.get("TPU_IR_SHINY_NEW_KNOB", "1")

        def other_env_fine():
            return os.environ.get("JAX_PLATFORMS")
    """, families=("contracts",))
    hits = [f for f in fs if f.rule == "TPU301"]
    assert len(hits) == 1 and "TPU_IR_SHINY_NEW_KNOB" in hits[0].message


def test_tpu301_subscript_and_from_import_forms(tmp_path):
    # the evasions the call-only check missed: subscript reads,
    # `from os import environ/getenv`, and setdefault; a subscript
    # STORE is a write, not a knob read
    fs = lint_src(tmp_path, """
        import os
        from os import environ, getenv

        def knobs():
            a = os.environ["TPU_IR_SUB_KNOB"]
            b = environ.get("TPU_IR_FROMIMP_KNOB")
            c = getenv("TPU_IR_GETENV_KNOB")
            d = os.environ.setdefault("TPU_IR_SETDEF_KNOB", "1")
            return a, b, c, d

        def writer():
            os.environ["TPU_IR_WRITTEN"] = "1"
    """, families=("contracts",))
    named = {m for f in fs if f.rule == "TPU301"
             for m in [f.message.split()[4]]}
    assert named == {"TPU_IR_SUB_KNOB", "TPU_IR_FROMIMP_KNOB",
                     "TPU_IR_GETENV_KNOB", "TPU_IR_SETDEF_KNOB"}


def test_tpu302_undeclared_accessor_read(tmp_path):
    fs = lint_src(tmp_path, """
        from tpu_ir.utils import envvars

        def knob():
            return envvars.get_int("TPU_IR_NOT_DECLARED")
    """, families=("contracts",))
    assert any(f.rule == "TPU302" and "TPU_IR_NOT_DECLARED" in f.message
               for f in fs)


def test_tpu303_undeclared_counter(tmp_path):
    fs = lint_src(tmp_path, """
        from tpu_ir.obs import get_registry
        from tpu_ir.utils.report import recovery_counters

        def emit():
            get_registry().incr("mystery.counter")
            recovery_counters().incr("retries")          # declared: ok
            recovery_counters().incr("typo_retries")     # not declared
    """, families=("contracts",))
    msgs = [f.message for f in fs if f.rule == "TPU303"]
    assert any("mystery.counter" in m for m in msgs)
    assert any("typo_retries" in m for m in msgs)
    assert not any("'retries'" in m for m in msgs)


def test_tpu303_undeclared_gauge(tmp_path):
    fs = lint_src(tmp_path, """
        from tpu_ir.obs import get_registry

        def emit():
            get_registry().set_gauge("mystery.level", 1.0)
            get_registry().update_gauge_max("mystery.peak", 2.0)
            get_registry().set_gauge("host.rss_bytes", 3.0)  # declared: ok
    """, families=("contracts",))
    msgs = [f.message for f in fs if f.rule == "TPU303"]
    assert any("mystery.level" in m for m in msgs)
    assert any("mystery.peak" in m for m in msgs)
    assert not any("host.rss_bytes" in m for m in msgs)


def test_profiled_jit_wrapped_functions_are_jit_roots():
    """obs/profiling.py's profiled_jit is the instrumented jax.jit
    drop-in (ISSUE 7): the index must keep treating its decorator and
    wrapper-assignment forms as jit roots — with static_argnames
    parsed — or every wrapped entry point silently leaves TPU1xx
    coverage."""
    index = PackageIndex(str(REPO / "tpu_ir"), rel_root=str(REPO))
    fns = {f.qual: f for m in index.modules.values()
           for f in m.functions.values()}
    tiered = fns["tfidf_topk_tiered"]
    assert tiered.jit_root
    assert {"k", "num_docs"} <= set(tiered.static_params)
    assert fns["build_postings_packed"].jit_root    # wrapper assignment
    assert fns["_sharded_topk_jit"].jit_root
    assert "mesh" in fns["_sharded_topk_jit"].static_params


def test_tpu304_undeclared_fault_site(tmp_path):
    fs = lint_src(tmp_path, """
        from tpu_ir import faults

        def risky():
            faults.maybe_crash("crash.not_a_real_site")
            faults.maybe_crash("crash.pass1")            # declared: ok
    """, families=("contracts",))
    hits = [f for f in fs if f.rule == "TPU304"]
    assert len(hits) == 1 and "crash.not_a_real_site" in hits[0].message


def test_tpu305_undeclared_span(tmp_path):
    fs = lint_src(tmp_path, """
        from tpu_ir.obs import trace

        def serve():
            with trace("mystery_stage"):
                pass
            with trace("dispatch"):       # declared: ok
                pass
            with trace("build.custom"):   # declared family: ok
                pass
    """, families=("contracts",))
    hits = [f for f in fs if f.rule == "TPU305"]
    assert len(hits) == 1 and "mystery_stage" in hits[0].message


# ---------------------------------------------------------------------------
# the runtime OrderedLock (TSan-lite)
# ---------------------------------------------------------------------------


def test_ordered_lock_detects_seeded_inversion_deterministically():
    """A→B then B→A raises on the SECOND ordering, single-threaded —
    no deadlock interleaving required."""
    graph = _OrderGraph()
    a = OrderedLock("A", graph=graph)
    b = OrderedLock("B", graph=graph)
    with a:
        with b:
            pass
    with pytest.raises(LockOrderInversion) as ei:
        with b:
            with a:
                pass
        # the inner `with a` raises before blocking; release b cleanly
    assert "'A'" in str(ei.value) and "'B'" in str(ei.value)
    assert graph.inversions


def test_ordered_lock_consistent_nesting_and_rlock_reentry():
    graph = _OrderGraph()
    a = OrderedLock("A", graph=graph)
    b = OrderedLock("B", graph=graph)
    r = OrderedLock("R", reentrant=True, graph=graph)
    for _ in range(3):
        with a:
            with b:
                with r:
                    with r:       # legal re-entry
                        pass
    assert graph.inversions == []


def test_ordered_lock_nonreentrant_reacquire_raises():
    graph = _OrderGraph()
    a = OrderedLock("A", graph=graph)
    with pytest.raises(LockOrderInversion):
        with a:
            with a:
                pass


def test_ordered_lock_inversion_across_threads():
    """Thread 1 records A→B; thread 2's B→A is caught even though the
    two never actually contend."""
    graph = _OrderGraph()
    a = OrderedLock("A", graph=graph, strict=False)
    b = OrderedLock("B", graph=graph, strict=False)

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    th = threading.Thread(target=t1)
    th.start()
    th.join()
    t2()
    assert len(graph.inversions) == 1


def test_ordered_lock_failed_try_acquire_commits_no_edge():
    """try-lock-and-back-off in the "wrong" order cannot deadlock (the
    thread never blocks) — a FAILED non-blocking acquire must not
    poison the order graph for the legitimate reverse order."""
    graph = _OrderGraph()
    a = OrderedLock("A", graph=graph)
    b = OrderedLock("B", graph=graph)
    b._inner.acquire()          # make B busy so the try-acquire fails
    try:
        with a:
            assert b.acquire(blocking=False) is False
    finally:
        b._inner.release()
    # the legitimate order B -> A is NOT an inversion
    with b:
        with a:
            pass
    assert graph.inversions == []


def test_envvars_minimum_clamps_not_raises(monkeypatch):
    """Values below a declared minimum clamp (the pre-registry sites'
    max(1, ...) idiom) — several accessors run at module import time,
    where a raise would kill the whole CLI before argument parsing."""
    from tpu_ir.utils import envvars

    monkeypatch.setenv("TPU_IR_TRACE_SAMPLE", "0")
    assert envvars.get_int("TPU_IR_TRACE_SAMPLE") == 1
    monkeypatch.setenv("TPU_IR_SPOOL_INTERVAL", "0")
    assert envvars.get_float("TPU_IR_SPOOL_INTERVAL") == 0.1
    # malformed values still raise, naming the variable
    monkeypatch.setenv("TPU_IR_TRACE_SAMPLE", "banana")
    with pytest.raises(ValueError, match="TPU_IR_TRACE_SAMPLE"):
        envvars.get_int("TPU_IR_TRACE_SAMPLE")


def test_install_scopes_to_repo_code(monkeypatch, tmp_path):
    from tpu_ir.lint import ordered_lock

    graph = ordered_lock.install(monkeypatch, strict=True)
    lk = threading.Lock()          # created from repo test code: wrapped
    assert isinstance(lk, OrderedLock)
    # stdlib-created locks stay real: Semaphore's internals don't break
    sem = threading.Semaphore(2)
    assert sem.acquire(blocking=False)
    sem.release()
    with lk:
        pass
    assert graph.inversions == []


# ---------------------------------------------------------------------------
# baseline semantics
# ---------------------------------------------------------------------------


def _f(rule, file, line, message):
    return Finding(rule, file, line, message)


def test_baseline_matches_on_message_not_line(tmp_path):
    f1 = _f("TPU203", "pkg/a.py", 10, "lock X held across blocking IO")
    path = tmp_path / "bl.json"
    path.write_text(Baseline.render([f1]))
    bl = Baseline.load(str(path))
    moved = _f("TPU203", "pkg/a.py", 99, "lock X held across blocking IO")
    fresh, stale = bl.filter([moved])
    assert fresh == [] and stale == []


def test_baseline_count_absorbs_exactly_n(tmp_path):
    f1 = _f("TPU203", "pkg/a.py", 10, "same message")
    path = tmp_path / "bl.json"
    path.write_text(Baseline.render([f1, _f("TPU203", "pkg/a.py", 20,
                                            "same message")]))
    bl = Baseline.load(str(path))
    three = [_f("TPU203", "pkg/a.py", n, "same message")
             for n in (10, 20, 30)]
    fresh, _ = bl.filter(three)
    assert len(fresh) == 1    # the third occurrence is NEW


def test_baseline_reports_stale_entries(tmp_path):
    path = tmp_path / "bl.json"
    path.write_text(Baseline.render([_f("TPU203", "pkg/a.py", 1, "gone")]))
    bl = Baseline.load(str(path))
    fresh, stale = bl.filter([])
    assert fresh == [] and len(stale) == 1


def test_fix_baseline_preserves_reasons(tmp_path):
    f1 = _f("TPU203", "pkg/a.py", 1, "kept")
    path = tmp_path / "bl.json"
    first = json.loads(Baseline.render([f1]))
    first["findings"][0]["reason"] = "the lock exists to serialize this IO"
    path.write_text(json.dumps(first))
    rendered = Baseline.render([f1], Baseline.load(str(path)))
    assert "the lock exists to serialize this IO" in rendered


# ---------------------------------------------------------------------------
# CLI exit codes (0 clean / 1 findings / 2 usage)
# ---------------------------------------------------------------------------


def test_cli_exit_0_on_shipped_package(capsys):
    assert cli_main(["lint"]) == 0
    assert "0 finding(s)" in capsys.readouterr().err


def test_cli_exit_1_on_findings_and_json_shape(tmp_path, capsys):
    pkg = tmp_path / "fixpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "bad.py").write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def bad(x):
            if x > 0:
                return x
            return -x
    """))
    assert cli_main(["lint", str(pkg), "--json"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["findings"] and out["findings"][0]["rule"] == "TPU102"
    assert {"rule", "severity", "file", "line", "message"} <= set(
        out["findings"][0])


def test_cli_exit_2_on_usage_errors(tmp_path, capsys):
    assert cli_main(["lint", str(tmp_path / "nope")]) == 2
    bad = tmp_path / "bl.json"
    bad.write_text("{\"version\": 99}")
    assert cli_main(["lint", "--baseline", str(bad)]) == 2


def test_cli_env_table_and_locks(capsys):
    assert cli_main(["lint", "--env-table"]) == 0
    table = capsys.readouterr().out
    assert "TPU_IR_CACHE_REVALIDATE" in table and "TPU_IR_TRACE_RING" in table
    assert cli_main(["lint", "--locks"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert "tpu_ir.search.scorer.Scorer._lazy_lock" in report["locks"]
    assert isinstance(report["order_edges"], list)


# ---------------------------------------------------------------------------
# THE self-check: the analyzers gate the codebase that ships them
# ---------------------------------------------------------------------------


def test_shipped_package_is_lint_clean_under_checked_in_baseline():
    """Zero un-baselined findings over tpu_ir/ — removing any fix this
    PR shipped (scorer lock-across-dispatch, envvar centralization,
    counter declarations, RUNBOOK table) makes this fail with the
    corresponding rule id. Tier-1's `tpu-ir lint` gate."""
    findings = run_lint(str(REPO / "tpu_ir"), rel_root=str(REPO))
    baseline_path = REPO / "lint_baseline.json"
    baseline = (Baseline.load(str(baseline_path))
                if baseline_path.exists() else Baseline())
    fresh, _stale = baseline.filter(findings)
    assert not fresh, "un-baselined lint findings:\n" + "\n".join(
        str(f) for f in fresh)


def test_self_check_sees_the_package():
    """The gate is only meaningful if the index actually sees the
    package: jit roots, the lock inventory, and fault sites must all be
    non-trivial (a silently-empty scan must fail loudly here)."""
    from tpu_ir.lint import contracts

    index = PackageIndex(str(REPO / "tpu_ir"), rel_root=str(REPO))
    roots = [f for m in index.modules.values()
             for f in m.functions.values() if f.jit_root]
    assert len(roots) >= 10, "jit-root detection rotted"
    assert len(index.all_locks()) >= 10, "lock inventory rotted"
    assert len(contracts.collect_fault_sites(index)) >= 5, \
        "fault-site scan rotted"
    assert contracts.collect_service_levels(index) == {
        "full", "no_rerank", "hot_only", "shed"}
