"""SPMD tests on the 8-device virtual CPU mesh: the sharded build and the
sharded scorer must reproduce single-device results exactly (SURVEY.md §4
"golden cross-shard results must equal single-shard results")."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_ir.ops import PAD_TERM, build_postings_jit, dense_doc_matrix, tfidf_topk_dense
from tpu_ir.ops.scoring import bm25_topk_dense, cosine_rerank_dense, dense_tf_matrix, idf_weights
from tpu_ir.parallel import (
    make_mesh,
    make_sharded_tiered,
    put_sharded,
    shard_slices,
    sharded_build_postings,
    sharded_tiered_rerank,
    sharded_tiered_topk,
)

S = 8


def _synth(seed=0, n_tok=6000, vocab=150, ndocs=64, cap=1024):
    """Random corpus occurrences, doc-sharded: docs dealt round-robin."""
    rng = np.random.default_rng(seed)
    t = rng.integers(0, vocab, n_tok).astype(np.int32)
    d = rng.integers(1, ndocs + 1, n_tok).astype(np.int32)
    term_ids = np.full((S, cap), PAD_TERM, np.int32)
    doc_ids = np.zeros((S, cap), np.int32)
    fill = np.zeros(S, np.int32)
    for ti, di in zip(t, d):
        s = (di - 1) % S
        term_ids[s, fill[s]] = ti
        doc_ids[s, fill[s]] = di
        fill[s] += 1
    docs_per_shard = np.array(
        [len({di for di in d if (di - 1) % S == s}) for s in range(S)],
        np.int32)
    return t, d, term_ids, doc_ids, docs_per_shard, vocab, ndocs


def test_sharded_build_equals_single_device():
    t, d, term_ids, doc_ids, dps, vocab, ndocs = _synth()
    mesh = make_mesh(S)
    out = sharded_build_postings(
        term_ids, doc_ids, dps, vocab_size=vocab, total_docs=ndocs, mesh=mesh)

    assert int(np.asarray(out.num_docs)[0]) == ndocs
    assert int(np.asarray(out.dropped)[0]) == 0

    # single-device reference
    flat_cap = 8192
    ft = np.full(flat_cap, PAD_TERM, np.int32)
    fd = np.zeros(flat_cap, np.int32)
    ft[: len(t)] = t
    fd[: len(d)] = d
    ref = build_postings_jit(jnp.asarray(ft), jnp.asarray(fd),
                             vocab_size=vocab, num_docs=ndocs)
    ref_np = int(ref.num_pairs)
    ref_term = np.asarray(ref.pair_term)[:ref_np]
    ref_doc = np.asarray(ref.pair_doc)[:ref_np]
    ref_tf = np.asarray(ref.pair_tf)[:ref_np]
    ref_df = np.asarray(ref.df)

    # reassemble sharded output: shard s owns terms with id % S == s
    got = {}
    df_got = np.zeros(vocab, np.int64)
    pair_total = 0
    for s in range(S):
        npairs = int(np.asarray(out.num_pairs)[s])
        pair_total += npairs
        pt = np.asarray(out.pair_term)[s][:npairs]
        pd = np.asarray(out.pair_doc)[s][:npairs]
        ptf = np.asarray(out.pair_tf)[s][:npairs]
        assert ((pt % S) == s).all()
        df_got += np.asarray(out.df)[s]
        for tt, dd, ww in zip(pt, pd, ptf):
            got.setdefault(int(tt), []).append((int(dd), int(ww)))

    assert pair_total == ref_np
    np.testing.assert_array_equal(df_got, ref_df)
    for tid in range(vocab):
        lo = int(np.searchsorted(ref_term, tid, side="left"))
        hi = int(np.searchsorted(ref_term, tid, side="right"))
        want = list(zip(ref_doc[lo:hi].tolist(), ref_tf[lo:hi].tolist()))
        assert got.get(tid, []) == want, f"term {tid}"


def test_sharded_build_overflow_retry():
    t, d, term_ids, doc_ids, dps, vocab, ndocs = _synth(seed=3, n_tok=4000)
    mesh = make_mesh(S)
    # absurdly small starting capacity forces the doubling retry path
    out = sharded_build_postings(
        term_ids, doc_ids, dps, vocab_size=vocab, total_docs=ndocs,
        mesh=mesh, bucket_cap=128)
    assert int(np.asarray(out.dropped)[0]) == 0


@pytest.fixture(scope="module")
def _scoring_fixture():
    """Postings + the sharded tiered layout on the 8-device mesh, with a
    small hot budget so both the hot strip AND the cold tiers carry data."""
    t, d, term_ids, doc_ids, dps, vocab, ndocs = _synth(seed=1)
    flat_cap = 8192
    ft = np.full(flat_cap, PAD_TERM, np.int32)
    fd = np.zeros(flat_cap, np.int32)
    ft[: len(t)] = t
    fd[: len(d)] = d
    ref = build_postings_jit(jnp.asarray(ft), jnp.asarray(fd),
                             vocab_size=vocab, num_docs=ndocs)
    npairs = int(ref.num_pairs)
    pt = np.asarray(ref.pair_term)[:npairs]
    pd = np.asarray(ref.pair_doc)[:npairs]
    ptf = np.asarray(ref.pair_tf)[:npairs]
    df = np.asarray(ref.df)
    doc_len = np.asarray(ref.doc_len)

    mesh = make_mesh(S)
    lay = make_sharded_tiered(pt, pd, ptf, df, doc_len,
                              num_docs=ndocs, num_shards=S,
                              hot_budget=S * 9 * 4)
    lay = put_sharded(lay, mesh)
    assert np.asarray(lay.hot_rank).max() >= 0          # hot strip in use
    assert any(np.asarray(td).any() for td in lay.tier_docs)  # tiers in use
    queries = np.array([[0, 5, -1], [17, 3, 9], [149, -1, -1], [2, 2, 2]],
                       np.int32)
    return ref, pt, pd, ptf, vocab, ndocs, mesh, lay, queries


def test_sharded_tiered_tfidf_equals_single_device(_scoring_fixture):
    ref, pt, pd, ptf, vocab, ndocs, mesh, lay, queries = _scoring_fixture
    mat = dense_doc_matrix(jnp.asarray(pt), jnp.asarray(pd), jnp.asarray(ptf),
                           vocab_size=vocab, num_docs=ndocs)
    s_ref, d_ref = tfidf_topk_dense(jnp.asarray(queries), mat, ref.df,
                                    jnp.int32(ndocs), k=10)
    s_got, d_got = sharded_tiered_topk(
        jnp.asarray(queries), lay, ref.df, jnp.int32(ndocs), mesh=mesh, k=10)
    np.testing.assert_allclose(np.asarray(s_got), np.asarray(s_ref), rtol=1e-5)
    # doc ids equal wherever scores are distinct; compare sets per query
    for qi in range(queries.shape[0]):
        assert set(np.asarray(d_got)[qi].tolist()) == \
            set(np.asarray(d_ref)[qi].tolist())
    # compat int-idf flows through the sharded path too
    s_c, _ = sharded_tiered_topk(
        jnp.asarray(queries), lay, ref.df, jnp.int32(ndocs), mesh=mesh,
        k=10, compat_int_idf=True)
    s_cr, _ = tfidf_topk_dense(jnp.asarray(queries), mat, ref.df,
                               jnp.int32(ndocs), k=10, compat_int_idf=True)
    np.testing.assert_allclose(np.asarray(s_c), np.asarray(s_cr), rtol=1e-5)


def test_sharded_tiered_bm25_equals_single_device(_scoring_fixture):
    ref, pt, pd, ptf, vocab, ndocs, mesh, lay, queries = _scoring_fixture
    tf_mat = dense_tf_matrix(jnp.asarray(pt), jnp.asarray(pd),
                             jnp.asarray(ptf), vocab_size=vocab,
                             num_docs=ndocs)
    s_ref, d_ref = bm25_topk_dense(jnp.asarray(queries), tf_mat, ref.df,
                                   ref.doc_len, jnp.int32(ndocs), k=10)
    s_got, d_got = sharded_tiered_topk(
        jnp.asarray(queries), lay, ref.df, jnp.int32(ndocs), mesh=mesh,
        k=10, scoring="bm25")
    np.testing.assert_allclose(np.asarray(s_got), np.asarray(s_ref),
                               rtol=1e-5)
    for qi in range(queries.shape[0]):
        assert set(np.asarray(d_got)[qi].tolist()) == \
            set(np.asarray(d_ref)[qi].tolist())


def test_sharded_tiered_rerank_equals_single_device(_scoring_fixture):
    ref, pt, pd, ptf, vocab, ndocs, mesh, lay, queries = _scoring_fixture
    df = np.asarray(ref.df)
    idf = np.asarray(idf_weights(ref.df, ndocs))
    w = (1.0 + np.log(np.maximum(ptf, 1))) * idf[pt]
    sq = np.bincount(pd, weights=w * w, minlength=ndocs + 1)
    norms = np.sqrt(sq[: ndocs + 1]).astype(np.float32)

    # single-device two-stage pipeline
    tf_mat = dense_tf_matrix(jnp.asarray(pt), jnp.asarray(pd),
                             jnp.asarray(ptf), vocab_size=vocab,
                             num_docs=ndocs)
    _, cand = bm25_topk_dense(jnp.asarray(queries), tf_mat, ref.df,
                              ref.doc_len, jnp.int32(ndocs), k=16)
    mat = dense_doc_matrix(jnp.asarray(pt), jnp.asarray(pd), jnp.asarray(ptf),
                           vocab_size=vocab, num_docs=ndocs)
    s_ref, d_ref = cosine_rerank_dense(
        jnp.asarray(queries), mat, ref.df, jnp.asarray(norms), cand,
        jnp.int32(ndocs), k=5)

    s_got, d_got = sharded_tiered_rerank(
        jnp.asarray(queries), lay, ref.df, jnp.int32(ndocs),
        jnp.asarray(shard_slices(norms, num_docs=ndocs, num_shards=S)),
        mesh=mesh, k=5, candidates=16)
    np.testing.assert_allclose(np.asarray(s_got), np.asarray(s_ref),
                               rtol=1e-5)
    for qi in range(queries.shape[0]):
        assert set(np.asarray(d_got)[qi].tolist()) == \
            set(np.asarray(d_ref)[qi].tolist())


def test_mesh_helper():
    mesh = make_mesh()
    assert mesh.devices.size == S
    with pytest.raises(ValueError):
        make_mesh(9999)

def test_shard_slices_more_shards_than_blocks():
    """Shards whose doc range starts past num_docs must stay empty, not
    crash (10 docs over 8 shards leaves shards 5..7 with no docs)."""
    row = np.arange(11)
    out = shard_slices(row, num_docs=10, num_shards=8)
    assert out.shape == (8, 3)
    np.testing.assert_array_equal(out[0], [0, 1, 2])
    np.testing.assert_array_equal(out[4], [0, 9, 10])
    assert (out[5:] == 0).all()


def test_sharded_scorer_small_corpus(tmp_path):
    """Scorer.load(layout='sharded') on a corpus smaller than mesh*2 docs
    (empty trailing shards) must agree with the dense layout for all
    scorers."""
    from tpu_ir.index import build_index
    from tpu_ir.search import Scorer

    docs = {f"T-{i:02d}": f"alpha w{i} w{i % 3} beta" for i in range(10)}
    corpus = tmp_path / "c.trec"
    corpus.write_text("".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in docs.items()))
    idx = str(tmp_path / "idx")
    build_index([str(corpus)], idx, num_shards=2, compute_chargrams=False)
    dense = Scorer.load(idx, layout="dense")
    sharded = Scorer.load(idx, layout="sharded")
    for q, kwargs in [("alpha w1", {}), ("beta", {"scoring": "bm25"})]:
        g1 = dense.search_batch([q], **kwargs)[0]
        g2 = sharded.search_batch([q], **kwargs)[0]
        assert {d for d, _ in g1} == {d for d, _ in g2}, q
    r1 = dense.search_batch(["alpha beta"], rerank=4)[0]
    r2 = sharded.search_batch(["alpha beta"], rerank=4)[0]
    assert {d for d, _ in r1} == {d for d, _ in r2}


def test_sharded_serving_cache_fast_path(tmp_path, monkeypatch):
    """Distributed serving gets the same zero-shard-IO warm load as the
    single-device tiered layout: a sharded cache hit must serve TF-IDF,
    BM25 and rerank identically with load_shard forbidden."""
    import os

    from tpu_ir.index import build_index
    from tpu_ir.index import format as fmt
    from tpu_ir.search import Scorer

    rng = np.random.default_rng(3)
    words = ["w%03d" % i for i in range(80)]
    corpus = tmp_path / "c.trec"
    with open(corpus, "w") as f:
        for i in range(60):
            body = " ".join(rng.choice(words, 25))
            f.write(f"<DOC>\n<DOCNO> D-{i:03d} </DOCNO>\n<TEXT>\n{body}\n"
                    f"</TEXT>\n</DOC>\n")
    idx = str(tmp_path / "idx")
    build_index([str(corpus)], idx, k=1, chargram_ks=[],
                compute_chargrams=False)

    cold = Scorer.load(idx, layout="sharded")
    queries = ["w001 w005", "w010 w020"]
    want = {
        ("tfidf", None): cold.search_batch(queries, scoring="tfidf"),
        ("bm25", None): cold.search_batch(queries, scoring="bm25"),
        ("bm25", 7): cold.search_batch(queries, rerank=7),
    }
    assert os.path.isdir(os.path.join(
        idx, f"serving-sharded-{len(jax.devices())}"))

    def boom(*a, **k):
        raise AssertionError("sharded cache hit must not touch shards")

    monkeypatch.setattr(fmt, "load_shard", boom)
    warm = Scorer.load(idx, layout="sharded")
    assert warm._pairs_cols is None
    for (scoring, rr), expect in want.items():
        got = warm.search_batch(queries, scoring=scoring, rerank=rr)
        for g, e in zip(got, expect):
            assert [d for d, _ in g] == [d for d, _ in e], (scoring, rr)
            np.testing.assert_allclose([s for _, s in g],
                                       [s for _, s in e], rtol=1e-5)
