"""Continuous micro-batching acceptance suite (ISSUE 9).

The contract: a query scored inside ANY coalesced padded batch returns
scores, docids and tie-break order IDENTICAL to its solo dispatch —
across layouts, scoring models, and degradation variants — while the
scheduler actually coalesces concurrent callers (occupancy > 1), keeps
per-request semantics tagged per slot, never makes an idle solo caller
wait, and keeps the compiled-program universe CLOSED (steady-state
serving performs zero XLA compiles after the frontend's ladder
precompile).
"""

import threading
import time
import warnings

import numpy as np
import pytest

from tpu_ir.index import build_index
from tpu_ir.obs import get_registry, querylog
from tpu_ir.search import Scorer
from tpu_ir.serving import (
    BatchKey,
    CoalescingScheduler,
    ServingConfig,
    ServingFrontend,
    run_concurrency_sweep,
)

WORDS = ("salmon fishing river bears honey quick brown fox lazy dog "
         "market investor asset bond stock season rain forest".split())

# mixed shapes: hot+cold, cold-only, duplicates, unknown terms, empty —
# the same adversarial spread the explain matrix uses
QUERIES = [
    "common salmon",
    "salmon fishing river",
    "honey bears",
    "salmon salmon fishing",
    "zzznope salmon",
    "common",
    "stock market investor",
]

LADDER = (1, 4, 16)
WIDTH = 8


@pytest.fixture(scope="module")
def index_dir(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("batching")
    body = []
    for i in range(150):
        # "common" in every doc -> a real hot-strip row (df = N)
        text = "common " + " ".join(WORDS[(i + j) % len(WORDS)]
                                    for j in range(3 + i % 7))
        body.append(f"<DOC>\n<DOCNO> D-{i:04d} </DOCNO>\n<TEXT>\n"
                    f"{text}\n</TEXT>\n</DOC>\n")
    corpus = tmp / "corpus.trec"
    corpus.write_text("".join(body))
    out = str(tmp / "idx")
    build_index([str(corpus)], out, num_shards=3, compute_chargrams=False)
    return out


@pytest.fixture(scope="module")
def scorers(index_dir):
    out = {
        "dense": Scorer.load(index_dir, layout="dense"),
        "sparse": Scorer.load(index_dir, layout="sparse"),
        "sharded": Scorer.load(index_dir, layout="sharded"),
    }
    hr = np.asarray(out["sparse"].hot_rank)
    assert (hr >= 0).sum() >= 1, "fixture must have a non-empty hot strip"
    return out


def _solo(scorer, text, **kw):
    kw.setdefault("k", 5)
    return scorer.search_batch([text], **kw)[0]


def _batched(scorer, texts, **kw):
    """The exact coalesced-dispatch shape the scheduler uses: padded to
    the smallest rung, pinned width, rung-padded scheduled groups."""
    rung = next(r for r in LADDER if r >= len(texts))
    return scorer.search_batch(texts, k=5, pad_to=rung, width_floor=WIDTH,
                               rung_ladder=LADDER, **kw)


# ---------------------------------------------------------------------------
# bit-exactness: coalesced == solo, across the full matrix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("layout", ["dense", "sparse", "sharded"])
@pytest.mark.parametrize("scoring", ["tfidf", "bm25"])
def test_coalesced_batch_bit_exact_per_layout_and_scoring(
        scorers, layout, scoring):
    s = scorers[layout]
    solo = [_solo(s, t, scoring=scoring) for t in QUERIES]
    for size in (1, 3, len(QUERIES)):
        batched = _batched(s, QUERIES[:size], scoring=scoring)
        assert len(batched) == size
        for got, want, text in zip(batched, solo[:size], QUERIES):
            # full tuples: docids AND float scores AND order, bit-exact
            assert list(got) == list(want), (layout, scoring, text)


@pytest.mark.parametrize("layout", ["sparse", "sharded"])
def test_coalesced_batch_bit_exact_hot_only(scorers, layout):
    s = scorers[layout]
    solo = [_solo(s, t, scoring="tfidf", hot_only=True) for t in QUERIES]
    batched = _batched(s, QUERIES, scoring="tfidf", hot_only=True)
    for got, want, text in zip(batched, solo, QUERIES):
        assert list(got) == list(want), (layout, text)


def test_coalesced_batch_bit_exact_prune_off(index_dir):
    s = Scorer.load(index_dir, layout="sparse", prune=False)
    solo = [_solo(s, t, scoring="bm25") for t in QUERIES]
    batched = _batched(s, QUERIES, scoring="bm25")
    for got, want, text in zip(batched, solo, QUERIES):
        assert list(got) == list(want), text


def test_coalesced_batch_bit_exact_rerank(scorers):
    s = scorers["sparse"]
    solo = [_solo(s, t, rerank=25) for t in QUERIES]
    batched = _batched(s, QUERIES, rerank=25)
    for got, want, text in zip(batched, solo, QUERIES):
        assert list(got) == list(want), text


def test_donated_query_twins_bit_exact(scorers, monkeypatch):
    """TPU_IR_BATCH_DONATE=1 forces the donated-query kernel twins even
    on CPU (where XLA ignores the donation with a warning): identical
    math, identical floats."""
    monkeypatch.setenv("TPU_IR_BATCH_DONATE", "1")
    s = scorers["sparse"]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # "donated buffers not usable"
        batched = _batched(s, QUERIES, scoring="bm25",
                           donate_queries=True)
    monkeypatch.setenv("TPU_IR_BATCH_DONATE", "0")
    solo = [_solo(s, t, scoring="bm25") for t in QUERIES]
    for got, want, text in zip(batched, solo, QUERIES):
        assert list(got) == list(want), text


def test_explain_ks_per_slot(scorers):
    """explain depth is tagged per slot: only the slots that asked get
    a decomposition, and it matches the solo explain bit-exactly."""
    s = scorers["sparse"]
    batched = _batched(s, QUERIES[:3], scoring="tfidf",
                       explain_ks=[2, 0, 1])
    assert batched[0].explain is not None and len(batched[0].explain) == 2
    assert batched[1].explain is None
    assert batched[2].explain is not None and len(batched[2].explain) == 1
    for e, (key, score) in zip(batched[0].explain, batched[0]):
        assert e["contribution_sum"] == e["score"] == score


# ---------------------------------------------------------------------------
# the scheduler: coalescing, solo fast path, key separation, errors
# ---------------------------------------------------------------------------


def test_scheduler_coalesces_concurrent_callers(scorers):
    s = scorers["sparse"]
    fe = ServingFrontend(s, ServingConfig(
        max_concurrency=8, max_queue=16, coalesce=True,
        batch_ladder=LADDER, batch_width=WIDTH))
    solo = {t: list(_solo(s, t, scoring="bm25", k=10)) for t in QUERIES}
    before = get_registry().get("batch.coalesced")
    errors = []
    barrier = threading.Barrier(8)

    def client(ci):
        try:
            barrier.wait(10)
            for i in range(12):
                t = QUERIES[(ci + i) % len(QUERIES)]
                res = fe.search(t, scoring="bm25")
                assert list(res) == solo[t], t
                assert res.level == "full" and not res.degraded
        except BaseException as e:  # noqa: BLE001
            errors.append(repr(e))

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errors
    snap = fe.batcher.snapshot()
    assert snap["max_occupancy"] > 1, "coalescing never engaged"
    assert snap["coalesced"] + snap["solo_flush"] == snap["batches"]
    assert get_registry().get("batch.coalesced") > before
    assert fe.stats()["batching"]["max_occupancy"] == snap["max_occupancy"]


def test_idle_solo_query_never_pays_the_wait(scorers):
    """An idle arrival dispatches IMMEDIATELY — the bounded coalescing
    wait applies only to promoted leaders, so the solo path cannot
    regress by the wait bound."""
    s = scorers["sparse"]
    fe = ServingFrontend(s, ServingConfig(
        max_concurrency=4, coalesce=True, coalesce_wait_ms=500.0,
        batch_ladder=LADDER, batch_width=WIDTH))
    fe.search(QUERIES[0], scoring="bm25")  # warm
    t0 = time.perf_counter()
    fe.search(QUERIES[1], scoring="bm25")
    elapsed_ms = (time.perf_counter() - t0) * 1e3
    assert elapsed_ms < 400.0, (
        f"idle solo query paid the coalescing wait ({elapsed_ms:.1f} ms)")
    assert fe.batcher.snapshot()["solo_flush"] >= 2


def test_incompatible_keys_do_not_share_a_batch(scorers):
    """Requests whose BatchKey differs (here: scoring model) must never
    coalesce into one kernel call — they dispatch as separate batches,
    each still correct."""
    s = scorers["sparse"]
    sched = CoalescingScheduler(s, ladder=LADDER, width=WIDTH)
    solo_tf = list(_solo(s, QUERIES[0], scoring="tfidf", k=10))
    solo_bm = list(_solo(s, QUERIES[0], scoring="bm25", k=10))
    results = {}
    barrier = threading.Barrier(2)

    def go(scoring):
        barrier.wait(10)
        results[scoring] = sched.submit(
            QUERIES[0], k=10, scoring=scoring, rerank=None,
            hot_only=False, force_host=False, level="full")

    threads = [threading.Thread(target=go, args=(sc,), daemon=True)
               for sc in ("tfidf", "bm25")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert list(results["tfidf"]) == solo_tf
    assert list(results["bm25"]) == solo_bm
    snap = sched.snapshot()
    assert snap["batches"] == 2 and snap["max_occupancy"] == 1


def test_batch_error_reaches_every_caller(scorers, monkeypatch):
    """A dispatch that raises delivers the error to EVERY slot of the
    batch — no caller hangs, no result vanishes."""
    s = scorers["sparse"]
    sched = CoalescingScheduler(s, ladder=LADDER, width=WIDTH)
    boom = RuntimeError("injected batch failure")

    def exploding(*a, **kw):
        raise boom

    monkeypatch.setattr(s, "search_batch", exploding)
    outcomes = []
    barrier = threading.Barrier(3)

    def go(i):
        barrier.wait(10)
        try:
            sched.submit(QUERIES[i], k=5, scoring="tfidf", rerank=None,
                         hot_only=False, force_host=False, level="full")
            outcomes.append("ok")
        except RuntimeError as e:
            outcomes.append(str(e))

    threads = [threading.Thread(target=go, args=(i,), daemon=True)
               for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert outcomes == ["injected batch failure"] * 3
    assert sched.snapshot()["queued"] == 0
    assert not sched.snapshot()["dispatching"]


def test_phrase_queries_route_solo(scorers):
    sched = CoalescingScheduler(scorers["sparse"], ladder=LADDER,
                                width=WIDTH)
    with pytest.raises(ValueError):
        sched.submit('"salmon fishing"', k=5, scoring="tfidf",
                     rerank=None, hot_only=False, force_host=False,
                     level="full")
    # and the scorer-level guard: per-slot lists index the PLAIN batch,
    # so a phrase query mixed into a slot-tagged batch must be rejected
    # loudly, not silently shift every later slot's attribution
    with pytest.raises(ValueError):
        scorers["sparse"].search_batch(['"salmon fishing"', "honey"],
                                       explain_ks=[0, 1])
    assert BatchKey(5, "tfidf", None, False, False) != \
        BatchKey(5, "bm25", None, False, False)


def test_all_hot_batch_skips_the_pad_only_dispatch(scorers):
    """A batch whose every REAL query is hot must not pay a second
    dispatch just to score its rung-pad rows: one full-kernel call,
    results still bit-exact."""
    s = scorers["sparse"]
    texts = ["common", "common", "common"]  # df == N -> hot strip
    solo = [_solo(s, t, scoring="tfidf") for t in texts]
    calls = []
    orig = s._topk_device

    def counting(q, k, scoring, **kw):
        calls.append(len(q))
        return orig(q, k, scoring, **kw)

    s._topk_device = counting
    try:
        batched = _batched(s, texts, scoring="tfidf")
    finally:
        del s._topk_device
    assert len(calls) == 1, f"expected one dispatch, saw rows={calls}"
    for got, want in zip(batched, solo):
        assert list(got) == list(want)


# ---------------------------------------------------------------------------
# the closed compile universe + querylog wiring + sweep
# ---------------------------------------------------------------------------


def test_precompiled_ladder_closes_the_shape_universe(index_dir):
    """After the frontend's ladder precompile, steady-state coalesced
    serving performs ZERO jit compiles — stronger than the zero-
    recompiles acceptance pin: batch content (occupancy, scheduling
    split, query mix) cannot mint a single new XLA program."""
    s = Scorer.load(index_dir, layout="sparse")
    fe = ServingFrontend(s, ServingConfig(
        max_concurrency=6, max_queue=16, coalesce=True,
        batch_ladder=LADDER, batch_width=WIDTH))
    reg = get_registry()
    compiles_before = reg.get("compile.count")
    errors = []

    def client(ci):
        try:
            for i in range(10):
                fe.search(QUERIES[(ci + i) % len(QUERIES)],
                          scoring=("bm25" if i % 2 else "tfidf"))
        except BaseException as e:  # noqa: BLE001
            errors.append(repr(e))

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    assert not errors
    assert reg.get("compile.count") == compiles_before, (
        "steady-state coalesced serving compiled a new program")
    assert reg.get("compile.recompiles") == 0


def test_querylog_entries_carry_batch_attribution(scorers):
    """Every coalesced entry records queue_wait_ms + batch_occupancy,
    entries of one shared batch join on batch_id, and degradation is
    uniform within a batch (no slot charged a batch-mate's outcome)."""
    querylog.clear()
    s = scorers["sparse"]
    fe = ServingFrontend(s, ServingConfig(
        max_concurrency=6, max_queue=16, coalesce=True,
        batch_ladder=LADDER, batch_width=WIDTH))
    barrier = threading.Barrier(6)

    def client(ci):
        barrier.wait(10)
        for i in range(6):
            fe.search(QUERIES[(ci + i) % len(QUERIES)], scoring="bm25")

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    entries = [e for e in querylog.recent() if "batch_occupancy" in e]
    assert entries, "no coalesced entries recorded"
    by_batch: dict = {}
    occupancies = set()
    for e in entries:
        assert "queue_wait_ms" in e and e["queue_wait_ms"] >= 0.0
        assert e["level"] == "full"
        assert e["batch_occupancy"] >= 1
        occupancies.add(e["batch_occupancy"])
        by_batch.setdefault(e["batch_id"], []).append(e)
    assert any(o > 1 for o in occupancies), "no shared batch recorded"
    for batch_id, grp in by_batch.items():
        assert len({bool(g["degraded"]) for g in grp}) == 1, (
            f"mixed degraded verdicts inside batch {batch_id}")
        assert len({g["batch_occupancy"] for g in grp}) == 1
        # occupancy is the number of REAL slots in the shared dispatch
        assert len(grp) <= grp[0]["batch_occupancy"]


def test_concurrency_sweep_reports_and_guards(scorers):
    """The serve-bench sweep instrument: per-level latency/QPS/occupancy
    rows, a solo-RTT reference, and the zero-recompile pin."""
    rep = run_concurrency_sweep(
        scorers["sparse"], levels=(1, 4), queries_per_level=24, seed=1,
        scoring="bm25")
    assert rep["solo_rtt_ms"] > 0
    assert [lv["concurrency"] for lv in rep["levels"]] == [1, 4]
    for lv in rep["levels"]:
        assert lv["errors"] == 0
        assert lv["served"] > 0
        assert lv["qps"] > 0
        assert lv["p99_ms"] >= lv["p50_ms"] > 0
        assert lv["recompiles"] == 0
        assert lv["occupancy"]["count"] == lv["coalesced"] + lv["solo_flush"]
    assert rep["levels"][0]["occupancy_mean"] == 1.0


def test_serve_bench_sweep_cli(index_dir, tmp_path, monkeypatch, capsys):
    """`tpu-ir serve-bench --concurrency 1,2` runs the sweep, prints the
    report, and appends the sentry row to BENCH_HISTORY.jsonl."""
    import json

    from tpu_ir.cli import main

    monkeypatch.chdir(tmp_path)
    (tmp_path / "BENCH_HISTORY.jsonl").write_text("")
    rc = main(["serve-bench", index_dir, "--backend", "cpu",
               "--layout", "sparse", "--queries", "16",
               "--concurrency", "1,2", "--seed", "3"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert len(out["levels"]) == 2
    row = out["history_row"]
    # the config key carries sweep shape + corpus size (comparability
    # grouping), headlined by the LARGEST level regardless of order
    assert row["config"].startswith("serve_sweep-")
    assert row["config"].endswith("-c2")
    assert row["concurrency"] == 2
    assert {"batched_qps", "batched_p99_ms", "solo_p50_ms",
            "batch_occupancy_mean", "solo_rtt_ms",
            "recompiles"} <= set(row)
    lines = [json.loads(ln) for ln in
             (tmp_path / "BENCH_HISTORY.jsonl").read_text().splitlines()]
    assert len(lines) == 1 and lines[0]["config"] == row["config"]
    assert "ts" in lines[0]
