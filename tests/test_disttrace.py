"""Distributed request tracing + SLO burn-rate acceptance suite
(ISSUE 18).

The contract, unit-level (the routed chaos soak in test_router.py pins
the end-to-end version):

- **context**: traceparent mint/parse/adopt roundtrip, malformed
  headers degrade to untraced (never to a failed request), `use` is
  thread-local and restores, `child` parents under the exact attempt;
- **assembly**: a finished local span tree flattens under the
  installed context (root takes the context's span id), adopted roots
  link their remote parent and ALWAYS export; `stitch` merges the live
  store with the span spool deduped by span_id — the post-mortem path
  works after `drop()` wiped the live side;
- **tail sampling**: slow / partial / degraded / hedged / shed /
  errored roots are force-kept no matter the dice; boring minted roots
  fall to 1-in-N; TPU_IR_TRACE_TAIL=0 removes the force-keep;
- **joins**: querylog entries and flight-recorder headers carry the
  OPEN request's trace id (from the live context, not the ring), the
  coalescer's shared dispatch span appears once per member trace under
  the SAME span id;
- **SLO**: good iff full-quality within TPU_IR_SLO_P99_MS; the breach
  fires once per NOT-breached -> breached transition (multi-window
  rule); the fast burn arms the Autoscaler's scale-up.
"""

import json
import threading
import time

import pytest

from tpu_ir import obs
from tpu_ir.index import build_index
from tpu_ir.obs import disttrace, querylog
from tpu_ir.obs.aggregate import read_span_spool
from tpu_ir.obs.recorder import artifact_lines
from tpu_ir.obs.registry import get_registry
from tpu_ir.obs.server import MetricsServer
from tpu_ir.search import Scorer
from tpu_ir.serving import ServingConfig, ServingFrontend
from tpu_ir.serving.autoscale import Autoscaler, AutoscaleConfig
from tpu_ir.serving.shardset import rpc_post

WORDS = ("salmon fishing river bears honey quick brown fox lazy dog "
         "market investor asset bond stock season rain forest".split())


@pytest.fixture(scope="module")
def index_dir(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("disttrace")
    body = []
    for i in range(80):
        text = "common " + " ".join(WORDS[(i + j) % len(WORDS)]
                                    for j in range(3 + i % 5))
        body.append(f"<DOC>\n<DOCNO> D-{i:04d} </DOCNO>\n<TEXT>\n"
                    f"{text}\n</TEXT>\n</DOC>\n")
    corpus = tmp / "corpus.trec"
    corpus.write_text("".join(body))
    out = str(tmp / "idx")
    build_index([str(corpus)], out, num_shards=1,
                compute_chargrams=False)
    return out


# ---------------------------------------------------------------------------
# the context
# ---------------------------------------------------------------------------


def test_traceparent_mint_header_adopt_roundtrip():
    ctx = disttrace.mint()
    assert ctx is not None and not ctx.adopted
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    header = ctx.to_header()
    assert header == f"00-{ctx.trace_id}-{ctx.span_id}-01"
    tid, sid, flags = disttrace.parse_traceparent(header)
    assert (tid, sid, flags) == (ctx.trace_id, ctx.span_id, 1)
    worker = disttrace.adopt(header)
    assert worker.adopted
    assert worker.trace_id == ctx.trace_id
    assert worker.parent_id == ctx.span_id       # root links the caller
    assert worker.span_id != ctx.span_id         # but is its own span


@pytest.mark.parametrize("bad", [
    None, "", "garbage", "01-" + "a" * 32 + "-" + "b" * 16 + "-01",
    "00-short-" + "b" * 16 + "-01",              # trace_id wrong length
    "00-" + "a" * 32 + "-short-01",              # span_id wrong length
    "00-" + "z" * 32 + "-" + "b" * 16 + "-01",   # non-hex
    "00-" + "0" * 32 + "-" + "b" * 16 + "-01",   # all-zero trace id
    "00-" + "a" * 32 + "-" + "0" * 16 + "-01",   # all-zero span id
    "00-" + "a" * 32 + "-" + "b" * 16 + "-01-x",
])
def test_malformed_traceparent_degrades_to_untraced(bad):
    assert disttrace.parse_traceparent(bad) is None
    assert disttrace.adopt(bad) is None


def test_use_is_thread_local_and_restores():
    ctx = disttrace.mint()
    assert disttrace.current() is None
    with disttrace.use(ctx):
        assert disttrace.current() is ctx
        assert disttrace.current_trace_id() == ctx.trace_id
        inner = disttrace.mint()
        with disttrace.use(inner):
            assert disttrace.current() is inner
        assert disttrace.current() is ctx       # nested restore
        seen = []
        t = threading.Thread(
            target=lambda: seen.append(disttrace.current()))
        t.start()
        t.join(5)
        assert seen == [None]                   # other threads blind
    assert disttrace.current() is None
    with disttrace.use(None):                   # None is a free no-op
        assert disttrace.current() is None


def test_child_parents_under_the_attempt():
    ctx = disttrace.mint()
    att = disttrace.child(ctx)
    assert att.trace_id == ctx.trace_id
    assert att.parent_id == ctx.span_id
    assert att.span_id != ctx.span_id
    assert disttrace.child(None) is None


def test_disabled_mode_is_flag_tests_all_the_way_down():
    disttrace.configure(enabled=False)
    assert disttrace.mint() is None
    assert disttrace.adopt("00-" + "a" * 32 + "-" + "b" * 16 + "-01") \
        is None
    assert disttrace.add_span("a" * 32, "x") is None
    assert disttrace.piggyback("a" * 32) is None
    with disttrace.use(None):
        assert disttrace.current_trace_id() is None
    with obs.trace("request"):
        pass                                    # hook must not record
    assert disttrace.trace_ids() == []


# ---------------------------------------------------------------------------
# root-close flattening + tail sampling
# ---------------------------------------------------------------------------


def test_root_close_flattens_local_tree_under_context():
    disttrace.configure(sample=1)
    disttrace.set_service("unit")
    ctx = disttrace.mint()
    with disttrace.use(ctx), obs.trace("request", scoring="bm25") as r:
        r.set("level", "full")
        with obs.trace("ladder"):
            pass
        with obs.trace("dispatch"):
            pass
    spans = disttrace.spans_for(ctx.trace_id)
    by_name = {s["name"]: s for s in spans}
    root = by_name["request"]
    assert root["span_id"] == ctx.span_id       # the context IS the root
    assert root["parent_id"] is None            # minted: no remote parent
    assert root["attrs"]["level"] == "full"
    assert root["service"] == "unit"
    for name in ("ladder", "dispatch"):
        child = by_name[name]
        assert child["parent_id"] == ctx.span_id
        assert len(child["span_id"]) == 16
    assert len({s["span_id"] for s in spans}) == len(spans)


def test_standalone_roots_without_context_are_not_recorded():
    disttrace.configure(sample=1)
    before = set(disttrace.trace_ids())
    with obs.trace("ingest.wal_fsync"):         # no installed context
        pass
    assert set(disttrace.trace_ids()) == before


def test_sampling_drops_boring_and_keeps_nth():
    disttrace.configure(sample=1000)
    reg = get_registry()
    ctx = disttrace.mint()
    with disttrace.use(ctx), obs.trace("request"):
        pass
    assert ctx.trace_id not in disttrace.trace_ids()
    assert reg.get("disttrace.dropped_sampled") == 1
    disttrace.configure(sample=1)
    ctx2 = disttrace.mint()
    with disttrace.use(ctx2), obs.trace("request"):
        pass
    assert ctx2.trace_id in disttrace.trace_ids()
    assert reg.get("disttrace.kept_sampled") == 1


@pytest.mark.parametrize("anomaly", ["slow", "error", "partial",
                                     "degraded", "hedges", "shed"])
def test_tail_rule_force_keeps_every_anomaly(anomaly):
    # the dice alone would drop EVERY trace at this rate — anything
    # kept below was kept by the tail rule
    disttrace.configure(sample=10_000, slo_ms=1.0)
    ctx = disttrace.mint()
    try:
        with disttrace.use(ctx), obs.trace("request") as r:
            if anomaly == "slow":
                time.sleep(0.003)
            elif anomaly == "error":
                raise RuntimeError("boom")
            elif anomaly == "hedges":
                r.set("hedges", 2)
            else:
                r.set(anomaly, True)
    except RuntimeError:
        pass
    assert ctx.trace_id in disttrace.trace_ids(), anomaly
    assert get_registry().get("disttrace.kept_tail") == 1


def test_trace_tail_zero_drops_anomalies_to_the_dice():
    disttrace.configure(sample=10_000, slo_ms=1.0, tail=False)
    ctx = disttrace.mint()
    with disttrace.use(ctx), obs.trace("request") as r:
        r.set("partial", True)
        time.sleep(0.003)
    assert ctx.trace_id not in disttrace.trace_ids()


def test_adopted_roots_always_keep_and_export(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_IR_TELEMETRY_DIR", str(tmp_path))
    disttrace.configure(sample=10_000)          # dice says drop
    minter = disttrace.mint()
    ctx = disttrace.adopt(minter.to_header())
    with disttrace.use(ctx), obs.trace("request"):
        pass
    spans = disttrace.spans_for(ctx.trace_id)
    assert spans, "adopted root was dropped by the minter's dice"
    root = spans[0]
    assert root["parent_id"] == minter.span_id  # links the remote parent
    spooled = read_span_spool(trace_id=ctx.trace_id)
    assert {s["span_id"] for s in spooled} == \
        {s["span_id"] for s in spans}


# ---------------------------------------------------------------------------
# add_span / annotate / store bounds
# ---------------------------------------------------------------------------


def test_annotate_late_binds_verdict_and_duration():
    ctx = disttrace.mint()
    sid = disttrace.add_span(ctx.trace_id, "rpc.search",
                             parent_id=ctx.span_id, dur_ms=0.0,
                             attrs={"shard": 0, "hedge": True})
    disttrace.annotate(ctx.trace_id, sid, dur_ms=12.5, outcome="won")
    (rec,) = disttrace.spans_for(ctx.trace_id)
    assert rec["dur_ms"] == 12.5
    assert rec["attrs"]["outcome"] == "won"
    assert rec["attrs"]["hedge"] is True
    # unknown ids are a silent no-op (harvest can outlive eviction)
    disttrace.annotate(ctx.trace_id, "feedfeedfeedfeed", outcome="lost")
    disttrace.annotate("f" * 32, sid, outcome="lost")


def test_store_evicts_oldest_trace_whole():
    disttrace.configure(max_traces=2)
    tids = ["%032x" % i for i in (1, 2, 3)]
    for t in tids:
        disttrace.add_span(t, "x")
    assert disttrace.trace_ids() == tids[1:]
    assert disttrace.spans_for(tids[0]) == []


def test_piggyback_ingest_remote_roundtrip_and_no_reexport():
    disttrace.set_service("worker-s0r0")
    minter = disttrace.mint()
    ctx = disttrace.adopt(minter.to_header())
    with disttrace.use(ctx), obs.trace("request") as r:
        r.set("k", 10)
    batch = disttrace.piggyback(ctx.trace_id)
    assert batch and all(r["trace_id"] == ctx.trace_id for r in batch)
    # the router's side: fold the batch in, stitch live
    disttrace.drop(ctx.trace_id)
    disttrace.ingest_remote(batch)
    got = disttrace.spans_for(ctx.trace_id)
    assert {r["span_id"] for r in got} == {r["span_id"] for r in batch}
    # remote-ingested records are NOT re-piggybacked — they already
    # live where they were born (double export = double-counted spans)
    assert disttrace.piggyback(ctx.trace_id) is None


# ---------------------------------------------------------------------------
# stitching: live + post-mortem
# ---------------------------------------------------------------------------


def test_stitch_merges_store_and_spool_deduped(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_IR_TELEMETRY_DIR", str(tmp_path))
    disttrace.configure(sample=1)
    disttrace.set_service("router")
    ctx = disttrace.mint()
    with disttrace.use(ctx), obs.trace("request") as r:
        r.set("level", "full")
        with obs.trace("dispatch"):
            pass
    live = disttrace.stitch(ctx.trace_id)
    assert live["span_count"] == 2
    assert len(live["roots"]) == 1
    root = live["roots"][0]
    assert root["name"] == "request"
    assert [c["name"] for c in root["children"]] == ["dispatch"]
    assert live["services"] == ["router"]
    assert live["dur_ms"] >= 0.0
    # post-mortem: the live store is gone, the spool alone suffices
    disttrace.drop(ctx.trace_id)
    dead = disttrace.stitch(ctx.trace_id)
    assert dead["span_count"] == 2
    assert {s["span_id"] for s in _flat(dead)} == \
        {s["span_id"] for s in _flat(live)}
    assert disttrace.stitch("f" * 32) is None   # unknown trace


def _flat(st):
    out, stack = [], list(st["roots"])
    while stack:
        n = stack.pop()
        out.append(n)
        stack.extend(n.get("children", ()))
    return out


def test_stitch_orphan_spans_surface_as_roots():
    tid = "a" * 32
    disttrace.add_span(tid, "rpc.search", parent_id="b" * 16)
    st = disttrace.stitch(tid, include_spool=False)
    assert st["span_count"] == 1
    assert st["roots"][0]["name"] == "rpc.search"   # orphan, not lost


# ---------------------------------------------------------------------------
# the joins: querylog, flight-recorder header, coalescer re-parent
# ---------------------------------------------------------------------------


def test_querylog_entries_carry_the_open_trace_id():
    ctx = disttrace.mint()
    with disttrace.use(ctx):
        entry = querylog.record({"query_hash": "cafe0001",
                                 "total_ms": 1.0})
    assert entry["trace_id"] == ctx.trace_id
    bare = querylog.record({"query_hash": "cafe0002", "total_ms": 1.0})
    assert "trace_id" not in bare               # untraced stays clean
    explicit = querylog.record({"query_hash": "cafe0003",
                                "total_ms": 1.0, "trace_id": "x" * 32})
    assert explicit["trace_id"] == "x" * 32     # a stamped id wins


def test_querylog_cli_trace_filter(capsys):
    from tpu_ir.cli import main
    ctx = disttrace.mint()
    with disttrace.use(ctx):
        querylog.record({"query_hash": "beef0001", "total_ms": 1.0})
    querylog.record({"query_hash": "beef0002", "total_ms": 1.0})
    assert main(["querylog", "--trace", ctx.trace_id]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["trace_filter"] == ctx.trace_id
    assert [e["query_hash"] for e in out["entries"]] == ["beef0001"]


def test_flight_header_reads_trace_id_from_live_context():
    """The ISSUE-18 bugfix pin: the header's join key comes from the
    OPEN request's thread-local context + current_root — NOT the ring,
    which may have evicted or sampled out the very request whose
    failure triggered the dump."""
    disttrace.configure(sample=10_000)          # ring would sample out
    ctx = disttrace.mint()
    with disttrace.use(ctx), obs.trace("request", scoring="bm25"):
        header = json.loads(artifact_lines("unit_incident")[0])
        assert header["trace_id"] == ctx.trace_id
        assert header["open_root"]["name"] == "request"
        assert header["open_root"]["attrs"]["scoring"] == "bm25"
    bare = json.loads(artifact_lines("unit_incident")[0])
    assert "trace_id" not in bare and "open_root" not in bare


def test_coalesced_batch_reparents_under_every_member_trace(index_dir):
    """The shared dispatch appears ONCE per member trace under the SAME
    span id (the batch_id join), each with its own batch.slot child —
    correlating two slow coalesced requests reduces to comparing one
    span id."""
    disttrace.configure(sample=1)
    scorer = Scorer.load(index_dir, layout="sparse")
    fe = ServingFrontend(scorer, ServingConfig(
        max_concurrency=8, max_queue=32, coalesce=True,
        batch_ladder=(1, 4, 16), batch_width=8))
    queries = ["common salmon", "salmon fishing river", "honey bears",
               "stock market investor"]
    n = 8
    barrier = threading.Barrier(n)
    ctxs, errors = [disttrace.mint() for _ in range(n)], []

    def client(ci):
        try:
            barrier.wait(10)
            with disttrace.use(ctxs[ci]):
                for i in range(6):
                    fe.search(queries[(ci + i) % len(queries)],
                              scoring="bm25")
        except BaseException as e:  # noqa: BLE001
            errors.append(repr(e))

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errors
    assert fe.batcher.snapshot()["max_occupancy"] > 1
    # group each trace's dispatch spans by span id across ALL traces
    members = {}  # dispatch span_id -> [(trace_id, rec)]
    slots = {}    # trace_id -> {parent dispatch ids of its slot spans}
    for ctx in ctxs:
        for rec in disttrace.spans_for(ctx.trace_id):
            if rec["name"] == "batch.dispatch":
                members.setdefault(rec["span_id"], []).append(
                    (ctx.trace_id, rec))
            elif rec["name"] == "batch.slot":
                slots.setdefault(ctx.trace_id, set()).add(
                    rec["parent_id"])
                assert "queue_wait_ms" in rec["attrs"]
    shared = {sid: mem for sid, mem in members.items()
              if len({t for t, _ in mem}) > 1}
    assert shared, "no dispatch span was shared across traces"
    for sid, mem in members.items():
        occ = {rec["attrs"]["occupancy"] for _, rec in mem}
        assert len(occ) == 1                    # one batch, one story
        # every member trace parents the shared span under ITS OWN
        # slot context, and owns a slot child hanging off the join id
        assert len({rec["parent_id"] for _, rec in mem}) == len(mem)
        for tid, rec in mem:
            assert sid in slots[tid]
            assert rec["attrs"]["batch_id"] == sid


# ---------------------------------------------------------------------------
# the SLO burn-rate tracker
# ---------------------------------------------------------------------------


def test_slo_good_is_full_quality_within_budget():
    disttrace.configure(slo_ms=100.0)
    assert disttrace.slo_record("full", 5.0) is True
    assert disttrace.slo_record("full", 500.0) is False           # slow
    assert disttrace.slo_record("full", 5.0,
                                classification="partial") is False
    assert disttrace.slo_record("degraded", 5.0,
                                classification="degraded") is False
    assert disttrace.slo_record("shed", 1.0, ok=False,
                                classification="shed") is False
    snap = disttrace.slo_snapshot()
    assert snap["windows"]["fast"]["total"] == 5
    assert snap["windows"]["fast"]["bad"] == 4
    assert snap["levels"]["full"] == {"good": 1, "bad": 2}
    assert snap["levels"]["shed"] == {"good": 0, "bad": 1}
    assert snap["good"] == 1 and snap["bad"] == 4


def test_slo_breach_fires_once_per_transition(tmp_path, monkeypatch):
    from tpu_ir.obs import recorder
    monkeypatch.setenv("TPU_IR_FLIGHT_DIR", str(tmp_path / "flight"))
    recorder.reset_rate_limit()
    disttrace.configure(slo_ms=100.0, burn_threshold=2.0,
                        min_samples=5, slo_target=0.9)
    reg = get_registry()
    for _ in range(6):
        disttrace.slo_record("full", 500.0)     # all bad: burn = 100x
    assert reg.get("slo.burn_breach") == 1      # fired on transition
    assert disttrace.slo_snapshot()["breached"] is True
    for _ in range(4):
        disttrace.slo_record("full", 500.0)     # still breached
    assert reg.get("slo.burn_breach") == 1      # ... not re-fired
    from tpu_ir.obs.recorder import recent_headers
    (hdr,) = recent_headers(str(tmp_path / "flight"))
    assert hdr["reason"] == "slo_burn_breach"
    assert hdr["extra"]["slo"]["breached"] is True
    # recovery clears the latch; a NEW burn episode fires again
    for _ in range(400):
        disttrace.slo_record("full", 1.0)
    assert disttrace.slo_snapshot()["breached"] is False
    recorder.reset_rate_limit()
    for _ in range(300):
        disttrace.slo_record("full", 500.0)
    assert reg.get("slo.burn_breach") == 2


class _Fleet:
    """The minimal lifecycle surface Autoscaler.tick reads/drives."""

    def __init__(self):
        self._replicas = 1

    def active_replicas(self, shard=None):
        return self._replicas

    def grow(self):
        self._replicas += 1
        return [(0, self._replicas - 1)]

    def retire_replica(self, shard, replica, *, drain_timeout_s=30.0):
        self._replicas -= 1
        return {"shard": shard, "replica": replica}


class _Admission:
    max_concurrency = 10

    def in_flight(self):
        return 0

    def queue_depth(self):
        return 0


class _Router:
    def __init__(self):
        self.admission = _Admission()

    def reset_breaker(self, shard, replica):
        pass


def test_slo_burn_arms_autoscaler_scale_up():
    """Latency degradation adds a replica even when occupancy alone
    would not: the burn signal feeds the SAME hysteresis counter."""
    fleet, router = _Fleet(), _Router()
    a = Autoscaler(fleet, router, AutoscaleConfig(
        min_replicas=1, max_replicas=3, cooldown_s=0.0,
        up_occupancy=0.8, down_occupancy=0.0, sustain_up=2,
        sustain_down=100, slo_burn_up=2.0))
    disttrace.configure(slo_ms=100.0)
    for _ in range(10):
        disttrace.slo_record("full", 500.0)     # burn = 100x
    assert disttrace.slo_burn_signal() >= 2.0
    d1 = a.tick(now=1.0)                        # occupancy is ~0
    assert d1["action"] is None and d1["slo_burn"] >= 2.0
    d2 = a.tick(now=2.0)
    assert d2["action"] == "up"
    assert d2["reason"] == "slo_burn"           # burn, not occupancy
    assert fleet.active_replicas() == 2


def test_slo_burn_signal_zero_disables_the_second_signal():
    fleet, router = _Fleet(), _Router()
    a = Autoscaler(fleet, router, AutoscaleConfig(
        min_replicas=1, max_replicas=3, cooldown_s=0.0,
        up_occupancy=0.8, down_occupancy=0.0, sustain_up=2,
        sustain_down=100, slo_burn_up=0.0))
    disttrace.configure(slo_ms=100.0)
    for _ in range(10):
        disttrace.slo_record("full", 500.0)
    for now in (1.0, 2.0, 3.0):
        assert a.tick(now=now)["action"] is None
    assert fleet.active_replicas() == 1


# ---------------------------------------------------------------------------
# the HTTP surface: /slo, /trace, /trace/<id>, RPC adoption
# ---------------------------------------------------------------------------


def _get(url, timeout=10.0):
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_server_slo_and_trace_endpoints():
    disttrace.configure(sample=1)
    disttrace.set_service("router")
    disttrace.slo_record("full", 1.0)
    ctx = disttrace.mint()
    with disttrace.use(ctx), obs.trace("request"):
        with obs.trace("dispatch"):
            pass
    with MetricsServer(port=0) as srv:
        code, body = _get(f"{srv.url}/slo")
        assert code == 200
        slo = json.loads(body)
        assert {"slo_p99_ms", "target", "windows", "breached",
                "levels"} <= set(slo)
        code, body = _get(f"{srv.url}/trace")
        assert code == 200
        assert ctx.trace_id in json.loads(body)["traces"]
        code, body = _get(f"{srv.url}/trace/{ctx.trace_id}")
        assert code == 200
        st = json.loads(body)
        assert st["span_count"] == 2
        assert st["roots"][0]["name"] == "request"
        code, body = _get(f"{srv.url}/trace/{ctx.trace_id}?format=html")
        assert code == 200
        page = body.decode()
        assert ctx.trace_id in page and "dispatch" in page
        code, _ = _get(f"{srv.url}/trace/{'f' * 32}")
        assert code == 404


def test_rpc_handler_adopts_traceparent_and_piggybacks():
    """The worker half of the wire contract: /rpc/<name> adopts the
    caller's traceparent, the handler's spans join the caller's trace,
    and the response carries the span batch (`_trace`) for live
    stitching — zero extra round trips."""
    disttrace.set_service("worker-s0r0")

    def handler(payload):
        with obs.trace("request") as r:
            r.set("k", payload.get("k"))
        return {"ok": True}

    ctx = disttrace.mint()
    attempt = disttrace.child(ctx)
    with MetricsServer(port=0, rpc_handlers={"search": handler}) as srv:
        out = rpc_post(f"{srv.host}:{srv.port}", "search", {"k": 7},
                       timeout_s=10.0,
                       headers={"traceparent": attempt.to_header()})
        assert out["ok"] is True
        batch = out["_trace"]
        assert all(r["trace_id"] == ctx.trace_id for r in batch)
        (root,) = [r for r in batch if r["name"] == "request"]
        assert root["parent_id"] == attempt.span_id
        assert root["attrs"]["k"] == 7
        assert root["service"] == "worker-s0r0"
        # untraced callers get a clean response — no _trace key
        bare = rpc_post(f"{srv.host}:{srv.port}", "search", {"k": 1},
                        timeout_s=10.0)
        assert "_trace" not in bare
