"""Durable ingest (ISSUE 17): WAL framing + corruption taxonomy, writer
leases, exactly-once SIGKILL crash recovery, backup/restore, and the
concurrent ingest+serve soak.

The heart is the crash matrix: a REAL child process is SIGKILLed (via
an injected fault converted to a raw SIGKILL — no unwind, no atexit) at
every declared ingest fault site, and the recovered live dir must be
bit-identical (final compacted segment checksums equal) to a control
writer that never crashed. Bit-identity across different flush
partitionings holds because merges are deterministic over the ordered
live-document list — the same property the merge-debt pins rely on.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from tpu_ir import obs
from tpu_ir.faults import IntegrityError
from tpu_ir.index.backup import backup_live, restore_live
from tpu_ir.index.ingest import IngestWriter
from tpu_ir.index.segments import LiveIndex
from tpu_ir.index.verify import verify_live
from tpu_ir.index.wal import (LEASE_FILE, WriteAheadLog, WriterLease,
                              WriterLeaseHeld, lease_holder, list_segments,
                              read_records, verify_wal, wal_dir)
from tpu_ir.serving.soak import _feed_doc, _spawn_feeder, run_ingest_soak


def _mklive(path) -> str:
    # chargram_ks=(): these tests pin durability semantics, not chargram
    # recall, and word-only builds keep every flush/compact cheap
    LiveIndex.create(str(path), num_shards=2, chargram_ks=())
    return str(path)


def _final_checksums(live_dir: str) -> dict:
    live = LiveIndex.open(live_dir)
    m = live.manifest(live.current_gen())
    assert len(m["segments"]) == 1, (
        f"expected one compacted segment, got {m['segments']}")
    meta_path = os.path.join(live.segment_path(m["segments"][0]),
                             "metadata.json")
    with open(meta_path, encoding="utf-8") as f:
        return json.load(f)["checksums"]


def _watermark(live_dir: str) -> int:
    live = LiveIndex.open(live_dir)
    return live.manifest(live.current_gen()).get("wal", {}).get("seq", 0)


# ---------------------------------------------------------------------------
# WAL framing: append / read / rotate / retire
# ---------------------------------------------------------------------------


def test_wal_append_read_rotate_retire(tmp_path):
    d = str(tmp_path)
    reg = obs.get_registry()
    fsyncs0 = reg.get("ingest.wal_fsyncs")
    w = WriteAheadLog(d, fsync_docs=2, fsync_ms=1e9)
    for i in range(5):
        seq = w.append({"op": "add", "docid": f"D{i}", "text": "x"},
                       key=f"D{i}")
        assert seq == i + 1
    assert w.last_seq == 5
    # fsync batching: 5 appends at fsync_docs=2 -> at least 2 syncs
    assert reg.get("ingest.wal_fsyncs") - fsyncs0 >= 2

    records, info = read_records(d)
    assert [s for s, _ in records] == [1, 2, 3, 4, 5]
    assert records[2][1]["docid"] == "D2"
    assert info["torn_tail"] is False

    # a watermark that does NOT cover the tail retires nothing
    retired0 = reg.get("ingest.wal_segments_retired")
    w.commit(3)
    assert reg.get("ingest.wal_segments_retired") == retired0
    assert read_records(d, after_seq=3)[0] == records[3:]

    # full coverage rotates the tail and retires the covered segment
    w.commit(5)
    assert reg.get("ingest.wal_segments_retired") == retired0 + 1
    segs = list_segments(d)
    assert len(segs) == 1 and segs[0][0] == 6
    assert read_records(d, after_seq=5)[0] == []

    # appends continue with monotonic sequence numbers after rotation
    assert w.append({"op": "add", "docid": "D5", "text": "x"},
                    key="D5") == 6
    w.close()
    assert verify_wal(d, watermark=5)["pending_records"] == 1


def test_wal_missing_or_empty_is_noop(tmp_path):
    d = str(tmp_path)
    records, info = read_records(d)   # no wal/ dir at all
    assert records == [] and info["segments"] == 0
    os.makedirs(wal_dir(d))
    assert read_records(d) == ([], info)
    # an empty (created-then-died) segment file scans clean too
    open(os.path.join(wal_dir(d), "wal-000000000001.log"), "w").close()
    records, info = read_records(d)
    assert records == [] and not info["torn_tail"]


# ---------------------------------------------------------------------------
# corruption taxonomy: torn tail vs mid-file rot
# ---------------------------------------------------------------------------


def _write_three(d: str) -> str:
    w = WriteAheadLog(d, fsync_docs=1)
    for i in range(3):
        w.append({"op": "add", "docid": f"D{i}", "text": "payload"},
                 key=f"D{i}")
    w.close()
    return list_segments(d)[0][1]


def test_torn_tail_truncates_and_continues(tmp_path):
    d = str(tmp_path)
    path = _write_three(d)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 5)   # the writer died mid-append of record 3

    # read-only scan REPORTS the tear without mutating the file
    records, info = read_records(d)
    assert [s for s, _ in records] == [1, 2]
    assert info["torn_tail"] and info["truncated_bytes"] > 0
    assert os.path.getsize(path) == size - 5

    # truncate_torn (the writer-open path) chops it loudly
    reg = obs.get_registry()
    torn0 = reg.get("ingest.wal_torn_tail_truncated")
    records, info = read_records(d, truncate_torn=True)
    assert [s for s, _ in records] == [1, 2]
    assert reg.get("ingest.wal_torn_tail_truncated") == torn0 + 1
    assert os.path.getsize(path) < size - 5

    # the next writer appends over clean bytes, reusing seq 3
    w = WriteAheadLog(d)
    assert w.append({"op": "add", "docid": "D2b", "text": "x"},
                    key="D2b") == 3
    w.close()
    assert [s for s, _ in read_records(d)[0]] == [1, 2, 3]


def test_midfile_bitrot_raises_integrity_error(tmp_path):
    d = str(tmp_path)
    path = _write_three(d)
    # flip one payload byte of record 1 — intact records FOLLOW the
    # damage, so this is rot, not a died writer, and must refuse replay
    with open(path, "r+b") as f:
        f.seek(20)
        byte = f.read(1)
        f.seek(20)
        f.write(bytes([byte[0] ^ 0xFF]))
    with pytest.raises(IntegrityError, match="seq"):
        read_records(d)
    with pytest.raises(IntegrityError):
        verify_wal(d)


# ---------------------------------------------------------------------------
# writer lease: conflict / stale takeover / dead-holder takeover
# ---------------------------------------------------------------------------


def _write_lease(d: str, pid: int, heartbeat: float) -> None:
    os.makedirs(wal_dir(d), exist_ok=True)
    with open(os.path.join(wal_dir(d), LEASE_FILE), "w") as f:
        json.dump({"pid": pid, "token": "foreign", "heartbeat": heartbeat},
                  f)


def test_lease_conflict_stale_and_dead_takeover(tmp_path):
    d = str(tmp_path)
    reg = obs.get_registry()

    # fresh heartbeat from a live foreign pid (pid 1 is always alive):
    # structured refusal carrying the holder and its heartbeat age
    _write_lease(d, 1, time.time())
    conflicts0 = reg.get("ingest.lease_conflicts")
    lease = WriterLease(d, ttl_s=30.0)
    with pytest.raises(WriterLeaseHeld) as ei:
        lease.acquire()
    assert ei.value.holder["pid"] == 1 and ei.value.age_s < 30.0
    assert reg.get("ingest.lease_conflicts") == conflicts0 + 1

    # stale heartbeat: takeover, with provenance of who was evicted
    _write_lease(d, 1, time.time() - 999.0)
    takeovers0 = reg.get("ingest.lease_takeovers")
    info = WriterLease(d, ttl_s=30.0).acquire()
    assert info["taken_over"] and info["previous_pid"] == 1
    assert reg.get("ingest.lease_takeovers") == takeovers0 + 1

    # fresh heartbeat but DEAD holder: takeover without waiting the TTL
    # (this is the crash-recovery path — SIGKILL stops the heartbeat
    # thread AND kills the pid)
    child = subprocess.Popen([sys.executable, "-c", "pass"])
    child.wait()
    _write_lease(d, child.pid, time.time())
    info = WriterLease(d, ttl_s=30.0).acquire()
    assert info["taken_over"] and info["previous_pid"] == child.pid

    holder = lease_holder(d)
    assert holder is not None and holder["pid"] == os.getpid()


def test_ingest_writer_refuses_foreign_live_lease(tmp_path):
    d = _mklive(tmp_path / "live")
    _write_lease(d, 1, time.time())
    with pytest.raises(WriterLeaseHeld):
        IngestWriter(d, auto_merge=False)
    os.unlink(os.path.join(wal_dir(d), LEASE_FILE))
    with IngestWriter(d, auto_merge=False) as w:
        assert not w.lease_info["taken_over"]
    # a clean close releases the lease
    assert lease_holder(d) is None


# ---------------------------------------------------------------------------
# replay: crash image -> bit-for-bit writer state
# ---------------------------------------------------------------------------


def test_replay_recovers_mixed_ops(tmp_path):
    d = _mklive(tmp_path / "live")
    w = IngestWriter(d, buffer_docs=100, auto_merge=False)
    w.add("D1", "alpha text")
    w.add("D2", "beta text")
    w.update("D1", "alpha prime")
    assert w.delete("D2")
    w.abandon()   # crash image: lease file left, WAL unsynced-to-manifest

    w2 = IngestWriter(d, buffer_docs=100, auto_merge=False)
    assert w2.replayed == 4
    # same-pid reacquire is quiet (in-process discipline is the
    # caller's); cross-PROCESS takeover is pinned by the crash matrix
    assert not w2.lease_info["taken_over"]
    assert w2.buffered() == 1   # D1 survives, D2 add+delete cancels
    w2.flush()
    assert _watermark(d) == 4
    assert set(w2.live.live_doc_map()) == {"D1"}
    w2.close()

    # replay is not re-logging: a third open has nothing left to replay
    with IngestWriter(d, auto_merge=False) as w3:
        assert w3.replayed == 0


def test_replay_idempotent_when_rekilled_mid_replay(tmp_path):
    d = _mklive(tmp_path / "live")
    w = IngestWriter(d, buffer_docs=100, auto_merge=False)
    for i in range(5):
        w.update(*_feed_doc(i))
    w.abandon()

    # recovery with a tiny buffer flushes MID-REPLAY (watermark
    # advances inside the replay loop) — then dies again before doing
    # any new work: the classic repeated-crash-during-recovery case
    w2 = IngestWriter(d, buffer_docs=2, auto_merge=False)
    assert w2.replayed == 5
    mid_watermark = _watermark(d)
    assert 0 < mid_watermark < 5   # some flushes landed mid-replay
    w2.abandon()

    # the third writer replays ONLY the suffix past the watermark
    w3 = IngestWriter(d, buffer_docs=2, auto_merge=False)
    assert w3.replayed == 5 - mid_watermark
    w3.flush()
    assert _watermark(d) == 5
    assert set(w3.live.live_doc_map()) == {
        _feed_doc(i)[0] for i in range(5)}
    w3.close()
    verify_live(d)


def test_wal_disabled_path(tmp_path):
    d = _mklive(tmp_path / "live")
    with IngestWriter(d, buffer_docs=2, auto_merge=False, wal=False) as w:
        w.update("D1", "alpha text")
        w.update("D2", "beta text")   # auto-flush at 2
        assert w.wal is None
    assert not os.path.exists(os.path.join(wal_dir(d), LEASE_FILE))
    assert _watermark(d) == 0   # inherited, never advanced
    live = LiveIndex.open(d)
    assert set(live.live_doc_map()) == {"D1", "D2"}


# ---------------------------------------------------------------------------
# satellite pins: tombstone-aware flush, gc-on-open, doctor warning
# ---------------------------------------------------------------------------


def test_pure_delete_feed_auto_flushes(tmp_path):
    d = _mklive(tmp_path / "live")
    with IngestWriter(d, buffer_docs=3, auto_merge=False) as w:
        for i in range(6):
            w.update(*_feed_doc(i))
        w.flush()
        gen0 = w.live.current_gen()
        # a pure-delete feed must flush on its own: tombstones count
        # toward the buffer threshold, adds are not required
        for i in range(3):
            assert w.delete(_feed_doc(i)[0])
        assert w.live.current_gen() > gen0
        assert w.pending_tombstones() == 0
        assert set(w.live.live_doc_map()) == {
            _feed_doc(i)[0] for i in range(3, 6)}


def test_gc_on_open_and_doctor_unreferenced_warning(tmp_path):
    from tpu_ir.index.doctor import live_doctor_report

    d = _mklive(tmp_path / "live")
    with IngestWriter(d, buffer_docs=1, auto_merge=False) as w:
        w.update(*_feed_doc(0))

    # strand a segment dir nothing references (a crashed half-build)
    junk = os.path.join(d, "segments", "seg-009999")
    os.makedirs(junk)
    with open(os.path.join(junk, "corpus.txt"), "w") as f:
        f.write("x" * 128)

    report = live_doctor_report(d)
    assert any(u["segment"] == "seg-009999"
               for u in report["unreferenced_segments"])
    assert any("unreferenced" in w_ for w_ in report["warnings"])
    assert "wal" in report

    # the next writer open gc's it away
    with IngestWriter(d, auto_merge=False):
        pass
    assert not os.path.exists(junk)
    assert live_doctor_report(d)["unreferenced_segments"] == []


# ---------------------------------------------------------------------------
# backup / restore
# ---------------------------------------------------------------------------


def test_backup_restore_carries_wal_tail(tmp_path):
    d = _mklive(tmp_path / "live")
    w = IngestWriter(d, buffer_docs=100, auto_merge=False)
    w.update(*_feed_doc(0))
    w.update(*_feed_doc(1))
    w.flush()
    w.compact_all()
    # two more docs acknowledged into the WAL but never flushed — the
    # backup must carry them (a snapshot is a portable crash image)
    w.update(*_feed_doc(2))
    w.update(*_feed_doc(3))
    w.abandon()

    bdir = str(tmp_path / "backup")
    summary = backup_live(d, bdir)
    assert summary["wal_segments"] >= 1 and summary["files"] > 3
    # a restore must never inherit the source machine's writer lease
    assert not os.path.exists(os.path.join(wal_dir(bdir), LEASE_FILE))

    rdir = str(tmp_path / "restored")
    report = restore_live(bdir, rdir)
    assert report["restored"] == os.path.abspath(rdir)
    assert report["wal"]["pending_records"] == 2

    with IngestWriter(rdir, auto_merge=False) as w2:
        assert w2.replayed == 2
        w2.flush()
        assert set(w2.live.live_doc_map()) == {
            _feed_doc(i)[0] for i in range(4)}

    # the source dir is untouched by the whole round trip
    assert verify_wal(d, watermark=_watermark(d))["pending_records"] == 2


def test_cli_backup_and_restore(tmp_path):
    from tpu_ir.cli import main as cli_main

    d = _mklive(tmp_path / "live")
    with IngestWriter(d, buffer_docs=1, auto_merge=False) as w:
        w.update(*_feed_doc(0))
        w.compact_all()
    bdir = str(tmp_path / "backup")
    rdir = str(tmp_path / "restored")
    assert cli_main(["backup", d, bdir]) == 0
    assert cli_main(["backup", bdir, rdir, "--restore"]) == 0
    assert set(LiveIndex.open(rdir).live_doc_map()) == {_feed_doc(0)[0]}


# ---------------------------------------------------------------------------
# THE SIGKILL crash matrix: every ingest fault site, bit-identical recovery
# ---------------------------------------------------------------------------

# one entry per ingest.* member of FAULT_SITES — the completeness pin
# below fails when a new ingest site is declared without matrix coverage
_MATRIX_SITES = (
    "ingest.wal_append",      # die before the record is framed
    "ingest.wal_torn",        # die mid-frame: physically torn tail
    "ingest.wal_retire",      # die mid WAL-segment retirement
    "ingest.flush_build",     # die after corpus write, before build
    "ingest.commit_between",  # die between manifest and CURRENT rename
    "ingest.merge",           # die mid-merge (compaction)
)

_MATRIX_DOCS = 10


def test_matrix_covers_every_ingest_fault_site():
    from tpu_ir.obs.registry import FAULT_SITES

    declared = {s for s in FAULT_SITES if s.startswith("ingest.")}
    assert declared == set(_MATRIX_SITES)


def _recover_and_finish(live_dir: str) -> None:
    """What an operator (or the soak's successor child) does after a
    crash: open (lease takeover + replay), then re-feed anything not
    yet acknowledged-and-recovered, flush, compact."""
    with IngestWriter(live_dir, buffer_docs=3, auto_merge=False) as w:
        w.flush()   # land whatever replay buffered
        have = w._docs()
        for i in range(_MATRIX_DOCS):
            docid, text = _feed_doc(i)
            if docid not in have:
                w.update(docid, text)
        w.flush()
        w.compact_all()


def test_sigkill_crash_matrix_bit_identical(tmp_path):
    # control: the same feed, never interrupted
    control = _mklive(tmp_path / "control")
    with IngestWriter(control, buffer_docs=3, auto_merge=False) as w:
        for i in range(_MATRIX_DOCS):
            w.update(*_feed_doc(i))
        w.flush()
        w.compact_all()
    want_docs = set(LiveIndex.open(control).live_doc_map())
    want_sums = _final_checksums(control)

    # crash children run CONCURRENTLY (each on its own live dir); the
    # fault plan fires once and ingest_feed_main converts the
    # InjectedCrash into a raw SIGKILL of the child itself
    kids = []
    for site in _MATRIX_SITES:
        d = _mklive(tmp_path / site.replace(".", "_"))
        ack = os.path.join(d, "feed.ack")
        open(ack, "w").close()
        proc, _out, err = _spawn_feeder(
            d, ack, 0, _MATRIX_DOCS, buffer_docs=3, compact_every=6,
            fault_plan=f"{site}:once@1")
        kids.append((site, d, ack, proc, err))

    reg = obs.get_registry()
    torn0 = reg.get("ingest.wal_torn_tail_truncated")
    for site, d, ack, proc, err in kids:
        rc = proc.wait(timeout=240)
        with open(err, encoding="utf-8") as f:
            tail = f.read()[-2000:]
        assert rc == -signal.SIGKILL, (
            f"{site}: child exited rc={rc} (site never fired?): {tail}")

        with open(ack, encoding="utf-8") as f:
            acked = [ln.strip() for ln in f if ln.strip()]

        _recover_and_finish(d)

        live = LiveIndex.open(d)
        got_map = live.live_doc_map()
        # zero acknowledged-write loss, and exactly-once: the recovered
        # dir is indistinguishable from the control at the byte level
        # (segment NAMES differ with the flush history; bytes must not)
        lost = [a for a in acked if a not in got_map]
        assert not lost, f"{site}: lost acked docs {lost}"
        assert set(got_map) == want_docs, f"{site}: doc set diverged"
        assert _final_checksums(d) == want_sums, (
            f"{site}: recovered segment is not bit-identical to control")
        report = verify_live(d)
        assert report["wal"]["pending_records"] == 0
        assert lease_holder(d) is None   # recovery writer closed cleanly

    # the torn-frame site must have actually produced (and recovered
    # from) a physically torn tail
    assert reg.get("ingest.wal_torn_tail_truncated") > torn0


# ---------------------------------------------------------------------------
# the ingest+serve soak (small tier-1 edition)
# ---------------------------------------------------------------------------


def test_ingest_soak_survives_midstream_sigkill(tmp_path):
    report = run_ingest_soak(
        str(tmp_path / "live"), docs=16, base_docs=6, buffer_docs=4,
        compact_every=8, timeout_s=150.0)
    assert report["kills"] == 1
    assert report["child_replayed"] >= 1      # the kill landed mid-work
    assert report["lease_takeover"]
    assert report["lost_acked"] == 0
    assert report["stale"] == 0 and report["errors"] == 0
    assert report["served"] + report["shed"] == report["submitted"]
    assert report["swaps"] >= 1 and report["freshness_samples"] >= 1
    assert report["ingest_docs_per_s"] > 0
    assert report["freshness_lag_ms"] > 0


# ---------------------------------------------------------------------------
# observability surfaces
# ---------------------------------------------------------------------------


def test_healthz_reports_ingest_durability(tmp_path):
    from tpu_ir.obs.server import health_snapshot

    d = _mklive(tmp_path / "live")
    with IngestWriter(d, buffer_docs=100, auto_merge=False) as w:
        w.update(*_feed_doc(0))
    snap = health_snapshot()
    ing = snap["ingest"]
    assert ing["wal_appends"] >= 1
    assert set(ing) >= {"wal_appends", "wal_fsyncs",
                        "wal_torn_tail_truncated", "wal_segments_retired",
                        "replayed", "lease_takeovers", "lease_conflicts"}
