"""Artifact format v2 (page-aligned zero-copy arenas) acceptance suite
(ISSUE 5): v1<->v2 round-trip parity at the scorer-result level, the
migrate-index CLI, verify-while-read (exactly ONE streamed pass over
part bytes on the verified load path), corruption faults against the v2
writer, mmap loads on a read-only index dir, load-thread-count
equivalence, and the chunked host-to-device streamer."""

import json
import os
import stat as stat_mod

import numpy as np
import pytest

import tpu_ir.faults as faults
from tpu_ir.cli import main
from tpu_ir.index import build_index
from tpu_ir.index import format as fmt
from tpu_ir.index.migrate import migrate_index
from tpu_ir.index.verify import verify_index
from tpu_ir.search import Scorer
from tpu_ir.utils.report import recovery_counters

WORDS = ("salmon fishing river bears honey quick brown fox lazy dog "
         "market investor asset bond stock season rain forest".split())

QUERIES = ("salmon fishing", "honey bears river", "stock market asset",
           "quick brown fox", "rain")


@pytest.fixture(autouse=True)
def _clean_state():
    faults.clear()
    recovery_counters().reset()
    fmt.reset_read_bytes()
    yield
    faults.clear()
    recovery_counters().reset()
    # disarm: the ledger must not stay on (per-chunk lock + growing
    # path dict) for every later test in the pytest process
    fmt.reset_read_bytes(arm=False)


def write_corpus(path, n_docs=90):
    body = []
    for i in range(n_docs):
        text = " ".join(WORDS[(i + j) % len(WORDS)]
                        for j in range(3 + (i % 7)))
        body.append(f"<DOC>\n<DOCNO> D-{i:04d} </DOCNO>\n<TEXT>\n"
                    f"{text}\n</TEXT>\n</DOC>\n")
    path.write_text("".join(body))
    return str(path)


def build(corpus, out, fv=None, monkeypatch=None):
    if fv is not None:
        assert monkeypatch is not None
        monkeypatch.setenv("TPU_IR_FORMAT_VERSION", str(fv))
    build_index([corpus], out, k=1, num_shards=3, compute_chargrams=False)
    if monkeypatch is not None:
        monkeypatch.delenv("TPU_IR_FORMAT_VERSION", raising=False)


def results(idx, layout="sparse"):
    s = Scorer.load(idx, layout=layout)
    return [s.search(q, k=10) for q in QUERIES]


# ---------------------------------------------------------------------------
# arena reader/writer unit behavior
# ---------------------------------------------------------------------------


def test_arena_roundtrip_eager_and_mmap(tmp_path):
    arrays = {
        "a": np.arange(1000, dtype=np.int32),
        "b": np.linspace(0, 1, 7)[None, :].astype(np.float32),
        "empty": np.zeros(0, np.int64),
        "scalarish": np.array([[5]], np.uint16),
    }
    path = str(tmp_path / "t.arena")
    fmt.write_arena(path, arrays)
    for mmap in (False, True):
        got = fmt.load_arena(path, mmap=mmap)
        assert list(got) == list(arrays)
        for k, a in arrays.items():
            assert got[k].dtype == a.dtype and got[k].shape == a.shape
            np.testing.assert_array_equal(np.asarray(got[k]), a)
    # every section starts page-aligned — the property that makes any
    # dtype memmap-able zero-copy
    header, data_start = fmt.read_arena_header(path)
    assert data_start % fmt.ARENA_ALIGN == 0
    for sec in header["sections"]:
        assert sec["offset"] % fmt.ARENA_ALIGN == 0


def test_arena_bitrot_raises_corrupt_taxonomy(tmp_path):
    path = str(tmp_path / "t.arena")
    fmt.write_arena(path, {"a": np.arange(4096, dtype=np.int32)})
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size - 100)
        byte = f.read(1)
        f.seek(size - 100)
        f.write(bytes([byte[0] ^ 0xFF]))
    with pytest.raises(fmt.CORRUPT_NPZ) as ei:
        fmt.load_arena(path)  # eager read verifies section CRCs
    assert "CRC mismatch" in str(ei.value)
    # truncation (torn write) surfaces too, as a section-past-EOF error
    with open(path, "r+b") as f:
        f.truncate(size // 2)
    with pytest.raises(fmt.CORRUPT_NPZ):
        fmt.load_arena(path)


def test_write_arena_atomic_shares_fault_sites(tmp_path):
    """The v2 writer rides the SAME spill_write retry + artifact_truncate
    sites as savez_atomic — PR-1 integrity semantics, new format."""
    path = str(tmp_path / "part-00000.arena")
    faults.install(faults.parse_plan("spill_write@part-:first@2"))
    crc = fmt.write_arena_atomic(path, a=np.arange(10, dtype=np.int32))
    assert recovery_counters().get("retries") == 2
    assert fmt.file_checksum(path) == crc  # CRC certifies renamed bytes
    faults.install(faults.parse_plan("artifact_truncate@part-:once@1"))
    crc2 = fmt.write_arena_atomic(path, a=np.arange(10, dtype=np.int32))
    assert fmt.file_checksum(path) != crc2  # post-rename corruption
    with pytest.raises(fmt.CORRUPT_NPZ):
        fmt.load_arena(path)


# ---------------------------------------------------------------------------
# v1 <-> v2 parity and migration
# ---------------------------------------------------------------------------


def test_v1_v2_scorer_parity(tmp_path, monkeypatch):
    """The SAME corpus built as npz (pinned v1) and as arenas (default)
    must produce byte-identical scorer results in every layout."""
    corpus = write_corpus(tmp_path / "c.trec")
    v1, v2 = str(tmp_path / "v1"), str(tmp_path / "v2")
    build(corpus, v1, fv=1, monkeypatch=monkeypatch)
    build(corpus, v2)
    assert fmt.IndexMetadata.load(v1).format_version == 1
    assert fmt.IndexMetadata.load(v2).format_version == 2
    assert os.path.exists(os.path.join(v1, "part-00000.npz"))
    assert os.path.exists(os.path.join(v2, "part-00000.arena"))
    assert verify_index(v1)["ok"] and verify_index(v2)["ok"]
    for layout in ("sparse", "dense"):
        assert results(v1, layout) == results(v2, layout), layout


def test_migrate_index_cli_roundtrip(tmp_path, monkeypatch, capsys):
    """v1 -> v2 migration in place: parts become arenas, checksums are
    re-recorded, results are identical; --to 1 rolls back; re-running is
    an idempotent no-op."""
    corpus = write_corpus(tmp_path / "c.trec")
    idx = str(tmp_path / "idx")
    build(corpus, idx, fv=1, monkeypatch=monkeypatch)
    want = results(idx)

    assert main(["migrate-index", idx]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] and out["migrated"] == 3 and out["skipped"] == 0
    meta = fmt.IndexMetadata.load(idx)
    assert meta.format_version == 2
    for s in range(3):
        assert os.path.exists(os.path.join(idx, f"part-{s:05d}.arena"))
        assert not os.path.exists(os.path.join(idx, f"part-{s:05d}.npz"))
        assert f"part-{s:05d}.arena" in meta.checksums
        assert f"part-{s:05d}.npz" not in meta.checksums
    assert verify_index(idx)["ok"]
    assert results(idx) == want

    # idempotent: a second run skips every shard
    assert main(["migrate-index", idx]) == 0
    assert json.loads(capsys.readouterr().out)["skipped"] == 3

    # rollback: --to 1 re-serializes to npz and re-pins the metadata
    assert main(["migrate-index", idx, "--to", "1"]) == 0
    assert json.loads(capsys.readouterr().out)["migrated"] == 3
    meta = fmt.IndexMetadata.load(idx)
    assert meta.format_version == 1
    assert os.path.exists(os.path.join(idx, "part-00000.npz"))
    assert verify_index(idx)["ok"]
    assert results(idx) == want


def test_migrate_refuses_corrupt_source(tmp_path, monkeypatch):
    """Migration must never launder rotten bytes into freshly
    re-checksummed artifacts — a corrupt source part is ONE structured
    IntegrityError, and the index is left un-migrated past it."""
    corpus = write_corpus(tmp_path / "c.trec")
    idx = str(tmp_path / "idx")
    build(corpus, idx, fv=1, monkeypatch=monkeypatch)
    part = os.path.join(idx, "part-00001.npz")
    size = os.path.getsize(part)
    with open(part, "r+b") as f:
        f.seek(size // 2)
        byte = f.read(1)
        f.seek(size // 2)
        f.write(bytes([byte[0] ^ 0xFF]))
    with pytest.raises(faults.IntegrityError) as ei:
        migrate_index(idx)
    assert "part-00001" in ei.value.path
    # metadata still pins v1: readers keep working off the old stamp
    assert fmt.IndexMetadata.load(idx).format_version == 1


def test_verify_passes_on_interrupted_migration(tmp_path, monkeypatch):
    """A migration killed mid-way leaves the converted shard's source
    unlinked while metadata checksums still name it. `tpu-ir verify`
    must pass on that dir (the twin is verified by its own internal
    CRCs), and re-running the migration completes it — the RUNBOOK §12
    contract. A genuinely missing shard (no twin) still fails."""
    corpus = write_corpus(tmp_path / "c.trec")
    idx = str(tmp_path / "idx")
    build(corpus, idx, fv=1, monkeypatch=monkeypatch)
    want = results(idx)
    meta = fmt.IndexMetadata.load(idx)

    # replay the migration's per-shard step for shard 0 only: arena
    # written + npz unlinked, metadata (checksums + stamp) NOT rewritten
    z = fmt.load_shard_verified(idx, 0, meta)
    fmt.save_shard(idx, 0, term_ids=z["term_ids"], indptr=z["indptr"],
                   pair_doc=z["pair_doc"], pair_tf=z["pair_tf"],
                   df=z["df"], format_version=2)
    assert os.path.exists(os.path.join(idx, "part-00000.arena"))
    assert not os.path.exists(os.path.join(idx, "part-00000.npz"))
    assert "part-00000.npz" in fmt.IndexMetadata.load(idx).checksums

    assert verify_index(idx)["ok"]  # twin self-verified, not "corrupt"
    # a bit-rotted twin is still caught by that self-verification: flip
    # a byte INSIDE a section (between-section alignment padding is not
    # CRC-covered)
    arena = os.path.join(idx, "part-00000.arena")
    header, data_start = fmt.read_arena_header(arena)
    sec = next(s for s in header["sections"] if s["nbytes"] > 0)
    pos = data_start + sec["offset"]
    with open(arena, "r+b") as f:
        f.seek(pos)
        byte = f.read(1)
        f.seek(pos)
        f.write(bytes([byte[0] ^ 0xFF]))
    with pytest.raises(faults.IntegrityError):
        verify_index(idx)
    fmt.save_shard(idx, 0, term_ids=z["term_ids"], indptr=z["indptr"],
                   pair_doc=z["pair_doc"], pair_tf=z["pair_tf"],
                   df=z["df"], format_version=2)  # restore good twin

    # re-running the migration finishes the job and results are intact
    out = migrate_index(idx)
    assert out["ok"] and out["migrated"] == 2 and out["skipped"] == 1
    assert verify_index(idx)["ok"]
    assert results(idx) == want

    # with the twin gone too, the missing-file error still surfaces
    os.remove(fmt.part_path(idx, 1))
    with pytest.raises(faults.IntegrityError) as ei:
        verify_index(idx)
    assert "missing" in str(ei.value)


def test_migrate_rerun_drops_stale_twin(tmp_path, monkeypatch):
    """A crash BETWEEN save_shard's rename and its twin-unlink leaves
    both formats' copies of one shard. Re-running the migration must
    drop the stale source twin (after self-verifying the kept target),
    not carry it in the checksum manifest forever."""
    import shutil

    corpus = write_corpus(tmp_path / "c.trec")
    idx = str(tmp_path / "idx")
    build(corpus, idx, fv=1, monkeypatch=monkeypatch)
    want = results(idx)
    meta = fmt.IndexMetadata.load(idx)

    npz = os.path.join(idx, "part-00000.npz")
    shutil.copyfile(npz, str(tmp_path / "keep.npz"))
    z = fmt.load_shard_verified(idx, 0, meta)
    fmt.save_shard(idx, 0, term_ids=z["term_ids"], indptr=z["indptr"],
                   pair_doc=z["pair_doc"], pair_tf=z["pair_tf"],
                   df=z["df"], format_version=2)  # unlinks the npz...
    shutil.copyfile(str(tmp_path / "keep.npz"), npz)  # ...resurrect it

    out = migrate_index(idx)
    assert out["ok"] and out["migrated"] == 2 and out["skipped"] == 1
    assert not os.path.exists(npz)
    assert os.path.exists(os.path.join(idx, "part-00000.arena"))
    meta2 = fmt.IndexMetadata.load(idx)
    assert "part-00000.npz" not in meta2.checksums
    assert "part-00000.arena" in meta2.checksums
    assert verify_index(idx)["ok"]
    assert results(idx) == want


# ---------------------------------------------------------------------------
# verify-while-read: exactly ONE streamed pass over part bytes
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fv", [1, 2])
def test_verified_load_single_streamed_pass(tmp_path, monkeypatch, fv):
    """The pin behind the tentpole: a verified cold Scorer.load streams
    each part file's bytes EXACTLY once (CRC fold and array parse share
    one read), for v1 npz and v2 arenas alike — the verify-then-read
    double scan is gone. The warm (cache-hit) load streams ZERO part
    bytes: it is mmap + upload only."""
    corpus = write_corpus(tmp_path / "c.trec")
    idx = str(tmp_path / "idx")
    build(corpus, idx, fv=fv, monkeypatch=monkeypatch)
    meta = fmt.IndexMetadata.load(idx)

    fmt.reset_read_bytes()
    cold = results(idx)  # cold: verified shard read + cache persist
    for s in range(meta.num_shards):
        path = fmt.part_path(idx, s)
        assert fmt.read_bytes_streamed(path) == os.path.getsize(path), \
            f"shard {s}: part bytes streamed more than once"

    fmt.reset_read_bytes()
    assert results(idx) == cold  # warm: serving-cache hit
    for s in range(meta.num_shards):
        assert fmt.read_bytes_streamed(fmt.part_path(idx, s)) == 0, \
            f"shard {s}: warm load touched part bytes"


def test_load_threads_equivalence(tmp_path, monkeypatch):
    """TPU_IR_LOAD_THREADS=1 and =8 must assemble identical CSR columns
    and serve identical results (the pool changes scheduling, never
    content)."""
    corpus = write_corpus(tmp_path / "c.trec")
    idx = str(tmp_path / "idx")
    build(corpus, idx)
    meta = fmt.IndexMetadata.load(idx)

    monkeypatch.setenv("TPU_IR_LOAD_THREADS", "1")
    df1, (pd1, ptf1) = Scorer._assemble_csr(idx, meta, verify=True)
    r1 = results(idx)
    monkeypatch.setenv("TPU_IR_LOAD_THREADS", "8")
    df8, (pd8, ptf8) = Scorer._assemble_csr(idx, meta, verify=True)
    np.testing.assert_array_equal(df1, df8)
    np.testing.assert_array_equal(pd1, pd8)
    np.testing.assert_array_equal(ptf1, ptf8)
    assert results(idx) == r1


# ---------------------------------------------------------------------------
# read-only serving + lazy pair_term
# ---------------------------------------------------------------------------


def test_mmap_load_on_readonly_index_dir(tmp_path, monkeypatch):
    """A deployed (read-only) index dir must serve: arena sections mmap
    with mode='r', the cache write is skipped, results are identical to
    a writable dir's."""
    corpus = write_corpus(tmp_path / "c.trec")
    idx = str(tmp_path / "idx")
    build(corpus, idx)
    want = results(idx)  # also persists the serving cache

    for root, _dirs, files in os.walk(idx):
        for f in files:
            os.chmod(os.path.join(root, f),
                     stat_mod.S_IRUSR | stat_mod.S_IRGRP)
    monkeypatch.setattr("tpu_ir.search.layout.serving_cache_writable",
                        lambda d: False)
    try:
        assert results(idx) == want  # warm: mmap'd cache hit
        # and the no-cache path too: a fresh verified shard load off the
        # same read-only files
        meta = fmt.IndexMetadata.load(idx)
        z = fmt.load_shard(idx, 0, mmap=True)
        assert not z["pair_doc"].flags.writeable
        df, _cols = Scorer._assemble_csr(idx, meta, verify=True)
        assert int(df.sum()) > 0
    finally:
        for root, _dirs, files in os.walk(idx):
            for f in files:
                os.chmod(os.path.join(root, f), 0o644)


def test_pair_term_stays_lazy_on_load(tmp_path):
    """The eager load must NOT materialize pair_term (~1 GB at 250M
    pairs); oracles that need it derive it on demand from df, and the
    derived column equals the np.repeat ground truth."""
    corpus = write_corpus(tmp_path / "c.trec")
    idx = str(tmp_path / "idx")
    build(corpus, idx)
    s = Scorer.load(idx, layout="sparse")
    assert s._pairs_cols is None or s._pairs_cols[0] is None
    pt, pd, ptf = s._pairs
    df = s._df_host()
    np.testing.assert_array_equal(
        pt, np.repeat(np.arange(len(df), dtype=np.int32), df))
    # doc/tf-only consumers never trigger the materialization
    s2 = Scorer.load(idx, layout="sparse")
    cols = s2._pairs_doc_tf
    assert len(cols) == 2 and s2._pairs_cols[0] is None


# ---------------------------------------------------------------------------
# serving-cache revalidation (stat-first, CRC fallback, param drift)
# ---------------------------------------------------------------------------


def test_cache_revalidation_stat_and_params(tmp_path):
    from tpu_ir.search.layout import load_serving_cache

    corpus = write_corpus(tmp_path / "c.trec")
    idx = str(tmp_path / "idx")
    build(corpus, idx)
    results(idx)  # persist the cache
    meta = fmt.IndexMetadata.load(idx)
    assert load_serving_cache(idx, meta=meta) is not None

    # mtime drift with identical content: the stat check misses but the
    # CRC fallback revalidates by content — still a hit
    part = fmt.part_path(idx, 0)
    os.utime(part, ns=(1, 1))
    assert load_serving_cache(idx, meta=meta) is not None

    # parameter drift must MISS even when file stats match (the key's
    # non-file fields are compared on the stat fast path too)
    assert load_serving_cache(idx, meta=meta, hot_budget=1) is None


def test_cache_revalidate_crc_catches_stat_preserving_rot(
        tmp_path, monkeypatch):
    """TPU_IR_CACHE_REVALIDATE=crc closes the one hole stat-first
    revalidation accepts by design: media bit-rot that preserves a
    part's size and mtime_ns rides a default-mode hit (a hit reads no
    part bytes at all), while crc mode re-streams every part's digest —
    the rotted part misses the cache into the eager verified path,
    which raises the structured integrity error."""
    from tpu_ir.search.layout import load_serving_cache

    corpus = write_corpus(tmp_path / "c.trec")
    idx = str(tmp_path / "idx")
    build(corpus, idx)
    results(idx)  # persist the cache
    meta = fmt.IndexMetadata.load(idx)

    # flip one byte mid-part, then restore mtime_ns: size + mtime now
    # match the manifest's part_stat exactly — invisible to a stat check
    part = fmt.part_path(idx, 0)
    st = os.stat(part)
    with open(part, "r+b") as f:
        f.seek(st.st_size - 100)
        byte = f.read(1)
        f.seek(st.st_size - 100)
        f.write(bytes([byte[0] ^ 0xFF]))
    os.utime(part, ns=(st.st_atime_ns, st.st_mtime_ns))
    assert os.stat(part).st_mtime_ns == st.st_mtime_ns

    # default stat-first mode: still a hit — the documented tradeoff
    # that buys the zero-part-IO warm load
    assert load_serving_cache(idx, meta=meta) is not None

    monkeypatch.setenv("TPU_IR_CACHE_REVALIDATE", "crc")
    assert load_serving_cache(idx, meta=meta) is None
    with pytest.raises(faults.IntegrityError):
        results(idx)

    # case/whitespace variants of the knob still count as crc; a bogus
    # value must raise, not silently fall back to the weaker stat mode
    monkeypatch.setenv("TPU_IR_CACHE_REVALIDATE", " CRC ")
    assert load_serving_cache(idx, meta=meta) is None
    monkeypatch.setenv("TPU_IR_CACHE_REVALIDATE", "full")
    with pytest.raises(ValueError, match="TPU_IR_CACHE_REVALIDATE"):
        load_serving_cache(idx, meta=meta)


# ---------------------------------------------------------------------------
# chunked host-to-device streaming
# ---------------------------------------------------------------------------


def test_stream_to_device_chunked_equivalence():
    import jax.numpy as jnp

    from tpu_ir.utils.transfer import stream_to_device

    rng = np.random.default_rng(7)
    for shape, dtype in (((1 << 15,), np.int32), ((257, 129), np.float32),
                         ((5,), np.uint16), ((0,), np.int32)):
        a = rng.integers(0, 100, size=shape).astype(dtype)
        got = stream_to_device(a, chunk_bytes=1 << 12)  # force chunking
        assert got.shape == a.shape and got.dtype == a.dtype
        np.testing.assert_array_equal(np.asarray(got), a)
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(jnp.asarray(a)))


def test_stream_to_device_verifies_crc():
    import zlib

    from tpu_ir.utils.transfer import stream_to_device

    a = np.arange(1 << 14, dtype=np.int32)
    good = f"crc32:{zlib.crc32(a.tobytes()):08x}"
    np.testing.assert_array_equal(
        np.asarray(stream_to_device(a, chunk_bytes=1 << 12,
                                    expected_crc=good)), a)
    with pytest.raises(faults.IntegrityError):
        stream_to_device(a, chunk_bytes=1 << 12,
                         expected_crc="crc32:00000000", label="t")


def test_h2d_telemetry_lands_in_registry():
    """Every stream_to_device call is a load.h2d span + h2d_bytes count,
    so effective bandwidth is readable from `tpu-ir metrics`."""
    from tpu_ir.obs import get_registry
    from tpu_ir.utils.transfer import stream_to_device

    reg = get_registry()
    reg.snapshot(reset=True)
    a = np.arange(1 << 13, dtype=np.int32)
    stream_to_device(a, chunk_bytes=1 << 12)
    snap = reg.snapshot()
    assert snap["counters"].get("load.h2d_bytes") == a.nbytes
    assert snap["histograms"]["load.h2d"]["count"] == 1
