"""TagTokenizer + analyzer parity tests.

Goldens follow the reference tokenizer's documented semantics
(org/galagosearch/core/parse/TagTokenizer.java; see tag_tokenizer.py header
for the rule list).
"""

from tpu_ir.analysis import TERRIER_STOPWORDS, analyze, tokenize


def test_reference_smoke_string():
    # the reference's own embedded smoke test (GalagoTokenizer.java:188-199)
    s = (" this is a the <test> for the teokenizer 101 546 "
         "345-543543545436-4656765865865 rgger <xml> ergtre 456435klj345lj34590")
    assert tokenize(s) == [
        "this", "is", "a", "the", "for", "the", "teokenizer", "101", "546",
        "345", "543543545436", "4656765865865", "rgger", "ergtre",
        "456435klj345lj34590",
    ]


def test_split_chars():
    assert tokenize("foo-bar_baz/qux:one,two") == [
        "foo", "bar", "baz", "qux", "one", "two"]
    # period and apostrophe are NOT split characters
    assert tokenize("don't") == ["dont"]
    assert tokenize("a.b.c") == ["abc"]  # acronym: periods at odd positions


def test_case_folding_and_apostrophes():
    assert tokenize("Hello WORLD") == ["hello", "world"]
    assert tokenize("O'Neill's") == ["oneills"]


def test_acronym_processing():
    assert tokenize("U.S.A.") == ["usa"]
    assert tokenize("I.B.M") == ["ibm"]
    assert tokenize("umass.edu") == ["umass", "edu"]
    # pieces of length 1 after a period split are dropped
    assert tokenize("Ph.D.") == ["ph"]
    assert tokenize("...") == []
    assert tokenize(".leading.trailing.") == ["leading", "trailing"]


def test_tags_stripped_and_script_ignored():
    assert tokenize("<DOC><TEXT>hello world</TEXT></DOC>") == ["hello", "world"]
    assert tokenize("a <script>var x = 99;</script> b") == ["a", "b"]
    assert tokenize("a <style>p {color: red}</style> b") == ["a", "b"]
    assert tokenize("a <script src='x.js'>ignored</script> b") == ["a", "b"]
    # self-closing ignored tag does not swallow the rest
    assert tokenize("a <script/> b") == ["a", "b"]
    # tagEnd search does not respect quotes (reference parseBeginTag uses a
    # plain indexOf(">")), so scanning resumes inside the quoted URL
    assert tokenize('<a href="http://x.com/page>weird">link text</a>') == [
        "weird", "link", "text"]


def test_comments_and_pis_skipped():
    assert tokenize("a <!-- hidden words --> b") == ["a", "b"]
    assert tokenize("a <?php echo 1 ?> b") == ["a", "b"]
    assert tokenize("a <!DOCTYPE html> b") == ["a", "b"]


def test_entities_skipped():
    assert tokenize("fish &amp; chips") == ["fish", "chips"]
    assert tokenize("x &#160; y") == ["x", "y"]
    # invalid escapes: '&' is just a split char
    assert tokenize("AT&T corp") == ["at", "t", "corp"]


def test_long_token_cap():
    # > 16 chars and >= 100 utf-8 bytes is dropped
    ascii_long = "a" * 101
    assert tokenize(ascii_long) == []
    # long but < 100 bytes survives
    assert tokenize("a" * 99) == ["a" * 99]
    # multibyte: 17 chars at 3 bytes each = 51 bytes -> survives
    assert tokenize("中" * 17) == ["中" * 17]
    # 34 chars * 3 bytes = 102 bytes -> dropped
    assert tokenize("中" * 34) == []


def test_unclosed_tag_does_not_crash():
    assert tokenize("hello <unclosed") == ["hello"]
    # a bare '< ' enters tag scanning and the scanner consumes through
    # 'w' — the reference state machine does the same, and the C++ twin
    # agrees (was asserted with a vacuous `== ... or True` before r5)
    assert tokenize("hello < world") == ["hello", "orld"]
    assert tokenize("<") == []
    assert tokenize("&") == []
    assert tokenize("") == []


def test_analyze_stopwords_and_stem():
    out = analyze("The running dogs are quickly jumping")
    assert out == ["run", "dog", "quick", "jump"]
    assert "the" in TERRIER_STOPWORDS and "are" in TERRIER_STOPWORDS
    # stopword filtering happens BEFORE stemming (reference order):
    # "things" is a stopword's plural, not filtered; "thing" is filtered.
    assert analyze("thing") == []
    assert len(TERRIER_STOPWORDS) == 733


def test_trec_doc_end_to_end():
    doc = ("<DOC>\n<DOCNO> FT911-3 </DOCNO>\n<TEXT>\n"
           "Contaminated water supplies affected thousands of refugees.\n"
           "</TEXT>\n</DOC>")
    assert analyze(doc) == [
        "ft911", "3", "contamin", "water", "suppli", "affect", "thousand",
        "refuge"]


def test_script_content_cannot_rearm_ignore():
    """Markup-looking text INSIDE an ignored <script>/<style> region must
    not change tokenizer state: document.write("<style>") used to overwrite
    ignore_until so the real </script> never matched and the rest of the
    document vanished (round-2 review finding)."""
    from tpu_ir.analysis.tag_tokenizer import tokenize

    assert tokenize('<script> document.write("<style>"); </script> '
                    'visible text here') == ["visible", "text", "here"]
    # comments/PIs inside the ignored region must not swallow the end tag
    assert tokenize("<script><!-- </lost --></script> shown") == ["shown"]
    assert tokenize("<style>a <?pi </style> b?> ignored</style> ok") \
        == ["ok"]
