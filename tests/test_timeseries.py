"""ISSUE 19 — the telemetry time machine.

Pins the tentpole's three load-bearing properties:

1. **Exact downsampling**: merging K fine windows is bit-identical to
   one coarse window over the same activity — counters, bucket arrays,
   sums, and every derived value (rate / level / percentile).
2. **Fixed memory**: the serialized store stops growing once the rings
   are full; ring lengths never exceed declared capacities.
3. **Quiet/loud anomaly contract**: zero flight records on a clean
   run, exactly one under an injected fault (the recorder's per-reason
   rate limit absorbs the repeats).

Plus the satellites that ride on the store: the sinusoid forecaster,
the autoscaler's third scale-up signal, the /timeseries endpoint +
dashboard, /healthz uptime/build fields, sampler lifecycle, declared
names, and the sampling-overhead bound.
"""

import json
import math
import os
import random
import time
import urllib.request

import pytest

from tpu_ir import obs
from tpu_ir.obs import timeseries as ts
from tpu_ir.obs.histogram import NUM_BUCKETS
from tpu_ir.obs.registry import (
    DECLARED_COUNTERS,
    GAUGE_MERGE,
    TIMESERIES_COUNTER_NAMES,
    get_registry,
)

TIERS = ((1, 24), (6, 8), (12, 4))


def _window(t, dur=1.0, c=None, g=None, h=None):
    return {"t": t, "dur_s": dur, "c": dict(c or {}), "g": dict(g or {}),
            "h": {k: (list(v[0]), v[1]) for k, v in (h or {}).items()}}


def _rand_window(rng, t):
    counts = [0] * NUM_BUCKETS
    for _ in range(rng.randrange(0, 6)):
        counts[rng.randrange(NUM_BUCKETS)] += rng.randrange(1, 4)
    return _window(
        t,
        dur=rng.choice([0.5, 1.0, 2.0]),
        c={"serving.submitted": rng.randrange(0, 50),
           "router.shed": rng.randrange(0, 5)},
        g={"router.occupancy": rng.random(),
           "slo.burn_fast": rng.random() * 4},
        h={"request": (counts, sum(counts) * 0.003)},
    )


# ---------------------------------------------------------------------------
# property 1: exact downsampling
# ---------------------------------------------------------------------------


def test_merge_windows_is_exact_rollup():
    """K fine windows merged == the single window a coarse sampler
    would have produced: identical raw materials, hence identical
    derived values. Randomized but seeded — a property test."""
    rng = random.Random(190)
    for trial in range(20):
        k = rng.choice([2, 3, 6])
        fines = [_rand_window(rng, t=100.0 + i) for i in range(k)]
        merged = ts.merge_windows(fines)
        # the coarse window built directly from the summed activity
        direct = _window(
            fines[-1]["t"],
            dur=sum(w["dur_s"] for w in fines),
            c={n: sum(w["c"].get(n, 0) for w in fines)
               for n in {n for w in fines for n in w["c"]}},
            g=fines[-1]["g"],     # both gauges declare "last"/absent
            h={"request": (
                [sum(w["h"]["request"][0][b] for w in fines)
                 for b in range(NUM_BUCKETS)],
                sum(w["h"]["request"][1] for w in fines))},
        )
        assert merged == direct, f"trial {trial}"
        # derived values agree too (rate, gauge, percentile)
        for kind, src in (("rate", "serving.submitted"),
                          ("gauge", "router.occupancy"),
                          ("p99", "request"), ("p50", "request")):
            assert ts.window_value(merged, kind, src) == \
                ts.window_value(direct, kind, src)


def test_store_rollup_matches_manual_merge():
    """The tier cascade IS merge_windows: tier-1 windows equal merging
    each consecutive factor-sized group of tier-0 windows by hand."""
    rng = random.Random(191)
    store = ts.TimeseriesStore(tiers=TIERS, sample_s=1.0)
    wins = [_rand_window(rng, t=200.0 + i) for i in range(24)]
    for w in wins:
        store.add_window(w)
    t1 = store.windows(1)
    assert len(t1) == 4
    for i, coarse in enumerate(t1):
        assert coarse == ts.merge_windows(wins[i * 6:(i + 1) * 6])
    # tier 2 rolls up pairs of tier-1 windows (12 // 6)
    t2 = store.windows(2)
    assert len(t2) == 2
    direct = ts.merge_windows(wins[0:12])
    # counters and bucket counts are integer sums — exactly equal; the
    # float sum_s differs only in association order (ulp-level)
    assert t2[0]["c"] == direct["c"]
    assert t2[0]["g"] == direct["g"]
    assert t2[0]["h"]["request"][0] == direct["h"]["request"][0]
    assert t2[0]["h"]["request"][1] == pytest.approx(
        direct["h"]["request"][1])
    assert (t2[0]["t"], t2[0]["dur_s"]) == (direct["t"], direct["dur_s"])


def test_cluster_merge_sums_deltas_not_durations():
    a = _window(10.0, dur=1.0, c={"serving.submitted": 10},
                g={"router.occupancy": 0.2})
    b = _window(10.4, dur=1.0, c={"serving.submitted": 30},
                g={"router.occupancy": 0.9})
    m = ts.merge_windows_across([a, b])
    assert m["dur_s"] == 1.0          # same wall window, max not sum
    assert m["c"]["serving.submitted"] == 40
    assert ts.window_value(m, "rate", "serving.submitted") == 40.0
    temporal = ts.merge_windows([a, b])
    assert temporal["dur_s"] == 2.0   # consecutive windows DO sum


# ---------------------------------------------------------------------------
# property 2: fixed memory
# ---------------------------------------------------------------------------


def test_footprint_bounded_once_rings_full():
    rng = random.Random(192)
    store = ts.TimeseriesStore(tiers=TIERS, sample_s=1.0)
    full = store.ring_limits()["max_windows"] * max(f for f, _ in TIERS)
    for i in range(full):
        store.add_window(_rand_window(rng, t=300.0 + i))
    size_full = len(json.dumps(store.state()))
    for i in range(full):
        store.add_window(_rand_window(rng, t=300.0 + full + i))
    size_2x = len(json.dumps(store.state()))
    # window payloads are randomized, so allow small jitter — the point
    # is no growth proportional to the second fill
    assert size_2x <= size_full * 1.05
    for tier in store.tier_layout():
        assert tier["len"] <= tier["capacity"]


def test_sampler_rebases_on_registry_reset():
    store = ts.TimeseriesStore(tiers=((1, 8),), sample_s=1.0)
    reg = get_registry()
    assert store.sample(now=1.0) is None       # first sample = baseline
    reg.incr("serving.submitted", 5)
    w = store.sample(now=2.0)
    assert w is not None and w["c"]["serving.submitted"] == 5
    reg.reset()                                 # bumps the resets stamp
    reg.incr("serving.submitted", 3)
    assert store.sample(now=3.0) is None        # rebase, not garbage
    reg.incr("serving.submitted", 2)
    w = store.sample(now=4.0)
    assert w is not None and w["c"]["serving.submitted"] == 2


def test_sample_overhead_is_cheap():
    """The acceptance bound is <=2% of a 10 s interval; pin an
    absolute per-sample cost far inside it (200 ms would be 2%)."""
    store = ts.TimeseriesStore(tiers=TIERS, sample_s=1.0)
    reg = get_registry()
    for i in range(40):
        reg.incr("serving.submitted")
        reg.observe("request", 0.004)
    store.sample(now=1.0)
    t0 = time.perf_counter()
    n = 50
    for i in range(n):
        reg.incr("serving.submitted")
        store.sample(now=2.0 + i)
    per_sample = (time.perf_counter() - t0) / n
    assert per_sample < 0.02, f"{per_sample * 1000:.2f} ms/sample"


# ---------------------------------------------------------------------------
# property 3: anomaly contract
# ---------------------------------------------------------------------------


def _steady_store(n=20, rate=10):
    store = ts.TimeseriesStore(tiers=((1, 32),), sample_s=1.0)
    for i in range(n):
        store.add_window(_window(400.0 + i, c={"serving.submitted": rate},
                                 g={"router.occupancy": 0.5}))
    return store


def test_anomaly_quiet_on_clean_history(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_IR_FLIGHT_DIR", str(tmp_path))
    store = _steady_store()
    assert store.detect_anomalies() == []
    assert list(tmp_path.iterdir()) == []
    assert get_registry().counters().get("timeseries.anomaly", 0) == 0


def test_anomaly_loud_exactly_once_under_fault(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_IR_FLIGHT_DIR", str(tmp_path))
    store = _steady_store()
    # injected fault: submitted rate collapses AND occupancy spikes
    store.add_window(_window(500.0, c={"serving.submitted": 500},
                             g={"router.occupancy": 0.5}))
    found = store.detect_anomalies()
    assert [f["series"] for f in found] == ["submitted_per_s"]
    assert abs(found[0]["z"]) >= 8.0
    flights = [p for p in tmp_path.iterdir() if "anomaly" in p.name]
    assert len(flights) == 1, "exactly one flight record"
    # the artifact header carries the lead-up timeseries block
    header = json.loads(flights[0].read_text().splitlines()[0])
    assert header["reason"] == "anomaly"
    assert header["extra"]["anomaly"]["series"] == "submitted_per_s"
    # sustained fault: detection repeats, the flight dump does NOT
    store.add_window(_window(501.0, c={"serving.submitted": 600},
                             g={"router.occupancy": 0.5}))
    again = store.detect_anomalies()
    assert again and again[0]["series"] == "submitted_per_s"
    flights = [p for p in tmp_path.iterdir() if "anomaly" in p.name]
    assert len(flights) == 1, "rate limit absorbed the repeat"
    assert get_registry().counters()["timeseries.anomaly"] == 2
    assert store.recent_anomalies()[-1]["series"] == "submitted_per_s"


def test_anomaly_floor_silences_flat_series(tmp_path, monkeypatch):
    """A near-constant series (MAD ~ 0) must not alarm on jitter —
    that is what the per-series floor is for."""
    monkeypatch.setenv("TPU_IR_FLIGHT_DIR", str(tmp_path))
    store = ts.TimeseriesStore(tiers=((1, 32),), sample_s=1.0)
    for i in range(20):
        store.add_window(_window(600.0 + i,
                                 g={"router.occupancy": 0.500}))
    store.add_window(_window(620.0, g={"router.occupancy": 0.52}))
    assert store.detect_anomalies() == []
    assert store.detect_anomalies(z_threshold=0) == []   # 0 disables


# ---------------------------------------------------------------------------
# the forecaster + the autoscaler's third signal
# ---------------------------------------------------------------------------


def test_fit_recovers_period_and_predicts_ahead():
    pts = [(50.0 + i * 2.0,
            0.5 + 0.3 * math.sin(2 * math.pi * (50.0 + i * 2.0) / 40.0))
           for i in range(40)]
    fit = ts.fit_sinusoid(pts)
    assert fit is not None and fit["r2"] > 0.9
    assert abs(fit["period_s"] - 40.0) < 4.0
    t = pts[-1][0] + 10.0
    truth = 0.5 + 0.3 * math.sin(2 * math.pi * t / 40.0)
    assert abs(ts.predict(fit, t) - truth) < 0.08


def test_fit_rejects_flat_and_noise():
    flat = [(float(i), 0.5) for i in range(20)]
    assert ts.fit_sinusoid(flat) is None
    rng = random.Random(193)
    noise = [(float(i), rng.random()) for i in range(20)]
    fit = ts.fit_sinusoid(noise)
    assert fit is None or fit["r2"] < 0.9


def test_forecaster_publishes_gauge_and_degrades():
    store = ts.TimeseriesStore(tiers=((1, 64),), sample_s=1.0)
    fc = ts.Forecaster(store, lead_s=10.0, interval_s=0.0)
    reg = get_registry()
    # sinusoidal occupancy history -> a confident forecast
    for i in range(30):
        t = 700.0 + i * 2.0
        store.add_window(_window(
            t, g={"router.occupancy":
                  0.5 + 0.3 * math.sin(2 * math.pi * t / 40.0)}))
    fc._t0 = 700.0
    now = 700.0 + 29 * 2.0
    value = fc.poll(now=now)
    assert value is not None
    truth = 0.5 + 0.3 * math.sin(2 * math.pi * (now + 10.0) / 40.0)
    assert abs(value - truth) < 0.12
    assert reg.gauges()["forecast_occupancy"] == pytest.approx(value)
    assert reg.counters()["forecast.fits"] >= 1
    assert store.last_fit["lead_s"] == 10.0
    # flat history -> gate fails -> gauge degrades to the current level
    store.reset()
    for i in range(20):
        store.add_window(_window(800.0 + i,
                                 g={"router.occupancy": 0.42}))
    fc2 = ts.Forecaster(store, lead_s=10.0, interval_s=0.0)
    fc2._t0 = 800.0
    assert fc2.poll(now=820.0) is None
    assert reg.gauges()["forecast_occupancy"] == pytest.approx(0.42)


def test_autoscaler_forecast_is_third_up_signal():
    from tests.test_autoscale import FakeFleet, FakeRouter, _cfg
    from tpu_ir.serving.autoscale import Autoscaler

    reg = get_registry()
    fleet, router = FakeFleet(), FakeRouter()
    scaler = Autoscaler(fleet, router, _cfg(sustain_up=2,
                                            forecast_up=0.6))
    router.admission.inflight = 3          # occupancy 0.3 < 0.8
    # low occupancy, no forecast gauge: no arming
    d = scaler.tick(now=10.0)
    assert d["action"] is None and d["forecast"] == 0.0
    assert reg.gauges()["router.occupancy"] == pytest.approx(0.3)
    # forecast predicts a burst: arms and fires with reason "forecast"
    reg.set_gauge("forecast_occupancy", 0.85)
    scaler.tick(now=11.0)
    d = scaler.tick(now=12.0)
    assert d["action"] == "up" and d["reason"] == "forecast"
    assert reg.counters()["forecast.scaleups"] == 1
    assert fleet.active_replicas() == 2
    # occupancy-driven scale-ups keep their own reason even when the
    # forecast gauge is also high
    fleet2, router2 = FakeFleet(), FakeRouter()
    scaler2 = Autoscaler(fleet2, router2, _cfg(sustain_up=1,
                                               forecast_up=0.6))
    router2.admission.inflight = 9
    d = scaler2.tick(now=20.0)
    assert d["action"] == "up" and d["reason"] == "sustained_pressure"


# ---------------------------------------------------------------------------
# lifecycle + surfaces
# ---------------------------------------------------------------------------


def test_sampler_thread_starts_and_stops():
    import threading

    sampler = ts.TimeseriesSampler(
        store=ts.TimeseriesStore(tiers=((1, 8),), sample_s=1.0),
        interval_s=0.01)
    sampler.start()
    names = [t.name for t in threading.enumerate()]
    assert "tpu-ir-obs-timeseries" in names
    time.sleep(0.05)
    sampler.stop()
    names = [t.name for t in threading.enumerate()]
    assert "tpu-ir-obs-timeseries" not in names
    assert get_registry().counters().get("timeseries.samples", 0) >= 1


def test_refcounted_sampler_survives_nested_servers():
    import threading

    s1 = ts.ensure_sampler()
    s2 = ts.ensure_sampler()
    assert s1 is s2 is not None
    ts.release_sampler()
    assert any(t.name == "tpu-ir-obs-timeseries"
               for t in threading.enumerate())
    ts.release_sampler()
    assert not any(t.name == "tpu-ir-obs-timeseries"
                   for t in threading.enumerate())


def test_disabled_flag_turns_everything_off(monkeypatch):
    monkeypatch.setenv("TPU_IR_TIMESERIES", "0")
    assert not ts.enabled()
    assert ts.ensure_sampler() is None
    assert ts.payload() == {"enabled": False}
    assert ts.header_window() is None


def _get_json(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return json.loads(resp.read().decode())


def test_timeseries_endpoint_and_healthz(tmp_path, monkeypatch):
    from tpu_ir.obs.server import MetricsServer

    reg = get_registry()
    store = ts.get_store()
    store.sample(now=time.time() - 1.0)
    reg.incr("serving.submitted", 7)
    reg.set_gauge("router.occupancy", 0.4)
    store.sample(now=time.time())
    with MetricsServer(port=0) as srv:
        base = f"http://127.0.0.1:{srv.port}"
        body = _get_json(f"{base}/timeseries")
        assert body["enabled"] and body["sources"] == 1
        assert body["tiers"][0]["len"] >= 1
        sub = body["series"]["submitted_per_s"]
        assert sub["kind"] == "rate"
        assert sub["tiers"][0], "tier-0 points present"
        occ = body["series"]["occupancy"]["tiers"][0]
        assert occ and occ[-1][1] == pytest.approx(0.4)
        html = urllib.request.urlopen(
            f"{base}/timeseries?format=html", timeout=5).read().decode()
        assert "<svg" in html and "/timeseries" in html
        assert "submitted_per_s" in html
        hz = _get_json(f"{base}/healthz")
        assert hz["uptime_s"] > 0
        assert hz["started_at"].count(":") == 2
        assert isinstance(hz["build_sha"], str)
        # the index page links the new route
        index = _get_json(f"{base}/")
        assert "/timeseries" in index["endpoints"]


def test_flight_header_carries_leadup(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_IR_FLIGHT_DIR", str(tmp_path))
    from tpu_ir.obs.recorder import flight_dump

    store = ts.get_store()
    store.sample(now=time.time() - 1.0)
    get_registry().incr("serving.submitted", 3)
    store.sample(now=time.time())
    path = flight_dump("test_leadup", force=True)
    header = json.loads(open(path).read().splitlines()[0])
    assert "timeseries" in header
    assert "submitted_per_s" in header["timeseries"]["series"]


def test_cluster_spool_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_IR_TELEMETRY_DIR", str(tmp_path))
    store = ts.get_store()
    reg = get_registry()
    now = time.time()
    store.sample(now=now - 1.0)
    reg.incr("serving.submitted", 10)
    store.sample(now=now)
    assert ts.spool_write_store(str(tmp_path)) is not None
    # forge a second process's spool file over the same wall window
    docs = ts.read_spool_stores(str(tmp_path))
    assert len(docs) == 1
    foreign = json.loads(json.dumps(docs[0]))
    foreign["run_id"] = "someone-else"
    foreign["pid"] = 99999
    with open(tmp_path / "timeseries-otherhost-99999.json", "w") as f:
        json.dump(foreign, f)
    body = ts.payload(cluster=True)
    assert body["sources"] == 2
    pts = body["series"]["submitted_per_s"]["tiers"][0]
    local = ts.payload(cluster=False)["series"]["submitted_per_s"]["tiers"][0]
    # cluster rate = sum of per-process rates over the same window
    assert pts[-1][1] == pytest.approx(2 * local[-1][1], rel=1e-3)


# ---------------------------------------------------------------------------
# declared names
# ---------------------------------------------------------------------------


def test_timeseries_names_are_declared():
    assert set(TIMESERIES_COUNTER_NAMES) <= set(DECLARED_COUNTERS)
    assert {"timeseries.samples", "timeseries.rollups",
            "timeseries.anomaly", "forecast.fits",
            "forecast.scaleups"} == set(TIMESERIES_COUNTER_NAMES)
    assert GAUGE_MERGE["router.occupancy"] == "last"
    assert GAUGE_MERGE["forecast_occupancy"] == "last"


def test_curated_sources_exist_in_registry_vocabulary():
    """Every curated counter source must be a declared counter name; a
    typo here would silently produce an all-zero series forever. The
    serving.* family is declared bare in SERVING_COUNTER_NAMES."""
    from tpu_ir.obs.registry import SERVING_COUNTER_NAMES

    serving = {f"serving.{n}" for n in SERVING_COUNTER_NAMES}
    for _, kind, source, _ in ts.CURATED:
        if kind == "rate":
            assert source in set(DECLARED_COUNTERS) | serving, source
