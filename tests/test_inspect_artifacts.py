"""Generic artifact inspection (VERDICT r3 item 7): every on-disk
artifact the framework writes — part/positions shards, build spills,
pass-1 manifests, serving caches, npy/tsv/json side files — has a
first-class `tpu-ir inspect` dump (the reference's ReadSequenceFile
generality, edu/umd/cloud9/io/ReadSequenceFile.java:36-38), with a
named-array listing as the fallback for any npz."""

import os

import numpy as np
import pytest

from tpu_ir.cli import main
from tpu_ir.index import format as fmt
from tpu_ir.index.artifacts import inspect_path
from tpu_ir.index.streaming import build_index_streaming

DOCS = {
    "I-01": "salmon fishing in rivers",
    "I-02": "quick brown fox jumps",
    "I-03": "salmon swim upstream today",
    "I-04": "market stocks fell sharply",
}


@pytest.fixture(scope="module")
def idx(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("inspect")
    p = tmp / "c.trec"
    p.write_text("".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in DOCS.items()))
    out = str(tmp / "idx")
    # streaming build with kept spills: the spill artifacts are part of
    # the inspection surface
    build_index_streaming([str(p)], out, k=1, num_shards=2, batch_docs=2,
                          compute_chargrams=True, chargram_ks=[2],
                          positions=True, keep_spills=True)
    return out


def lines_for(path, n=5):
    return list(inspect_path(path, n=n))


def test_inspect_positions_shard(idx):
    out = lines_for(os.path.join(idx, "positions-00000.npz"))
    assert "position runs" in out[0]
    assert any(line.startswith("run 0\t") for line in out)


def test_inspect_spill_artifacts(idx):
    spill = os.path.join(idx, "_spill")
    # tokens spill
    out = lines_for(os.path.join(spill, "tokens-00000.npz"))
    assert "token spill" in out[0] and "docs=" in out[0]
    # pairs spill
    out = lines_for(os.path.join(spill, "pairs-000-00000.npz"))
    assert "pair spill" in out[0]
    assert any(line.startswith("term=") for line in out[1:])
    # pos spill (streaming layout: same keys as a positions shard)
    out = lines_for(os.path.join(spill, "pos-000-00000.npz"))
    assert "position runs" in out[0]
    # pass-1 manifest: sig + batch shape
    out = lines_for(os.path.join(spill, "pass1.npz"))
    assert "pass-1 manifest" in out[0] and "n_batches=" in out[0]
    assert any(line.startswith("sig\t") for line in out)
    # the spill DIRECTORY lists its entries
    out = lines_for(spill)
    assert "directory" in out[0]
    assert any("tokens-00000.npz" in line for line in out)


def test_inspect_part_shard_standalone(idx):
    out = lines_for(os.path.join(idx, fmt.part_name(0)))
    assert "postings shard" in out[0]
    assert any(line.startswith("term_id=") for line in out[1:])


def test_inspect_side_files(idx):
    out = lines_for(os.path.join(idx, "doclen.npy"))
    assert "npy" in out[0] and "int32" in out[0]
    out = lines_for(os.path.join(idx, "metadata.json"))
    assert '"num_docs"' in out[0]
    out = lines_for(os.path.join(idx, fmt.DICTIONARY), n=2)
    assert len(out) == 3 and out[-1] == "..."


def test_inspect_unknown_npz_lists_arrays(tmp_path):
    path = str(tmp_path / "mystery.npz")
    np.savez(path, alpha=np.arange(20), beta=np.ones((3, 4), np.float32))
    out = lines_for(path)
    assert "arrays=2" in out[0]
    assert any(line.startswith("alpha\tint64\t(20,)") for line in out)
    assert any(line.startswith("beta\tfloat32\t(3, 4)") for line in out)


def test_inspect_serving_cache(idx, tmp_path):
    # force a tiered layout so the cache gets persisted, then dump it
    from tpu_ir.search import Scorer

    Scorer.load(idx, layout="sparse")
    cache = os.path.join(idx, "serving-tiered")
    assert os.path.isdir(cache)
    out = lines_for(cache)
    assert "serving cache" in out[0] and "version" in out[0]
    # the df line must carry the REAL head values — 'or startswith'
    # made the value check decorative, and the endswith arm could never
    # match (numpy-2 scalar reprs + the ' ...' suffix) (review r5).
    # Cache v5 packs every array into one arena; sections render as
    # cache.arena/<name> lines.
    from tpu_ir.index import format as fmt

    df = fmt.load_arena(os.path.join(cache, "cache.arena"))["df"]
    head = f"head={np.asarray(df[:8]).tolist()}"
    df_lines = [line for line in out
                if line.startswith("cache.arena/df\t")]
    assert df_lines and any(head in line for line in df_lines), out


def test_inspect_cli_dispatch(idx, capsys):
    # file path through the CLI
    assert main(["inspect", os.path.join(idx, "positions-00000.npz"),
                 "-n", "2"]) == 0
    assert "position runs" in capsys.readouterr().out
    # index dir keeps the dictionary-aware dump
    assert main(["inspect", idx, "-n", "2"]) == 0
    out = capsys.readouterr().out
    assert '"num_docs"' in out and "part-00000" in out
    # missing artifact: error, not traceback
    assert main(["inspect", str(idx) + "/nope.npz"]) == 1
