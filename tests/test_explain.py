"""Explain-vs-kernel bit-exact parity (ISSUE 8 acceptance).

The contract under test: for every hit a search returns, the explain
decomposition's float64-telescoped per-term contributions sum to the
production kernel's reported score BIT-exactly — across the dense /
tiered / doc-sharded layouts, tfidf / bm25 / compat-int-idf scoring,
and the hot_only / scheduled-static-skip / runtime-prune kernel
variants — and the explained docs appear in exactly the top-k's
tie-break order. The decomposition is exact by construction
(search/explain.py shares the kernels' accumulation expressions); these
tests are the tripwire that keeps that construction true as kernels
evolve.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_ir.index import build_index
from tpu_ir.search import Scorer
from tpu_ir.search.explain import explain_hits

WORDS = ("salmon fishing river bears honey quick brown fox lazy dog "
         "market investor asset bond stock season rain forest".split())

# mixed shapes: hot+cold, cold-only (the scheduled static-skip path),
# duplicate slots, unknown terms, hot-term-only, empty-after-analysis
QUERIES = [
    "common salmon",
    "salmon fishing river",
    "honey bears",
    "salmon salmon fishing",
    "zzznope salmon",
    "common",
]


@pytest.fixture(scope="module")
def index_dir(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("explain")
    body = []
    for i in range(150):
        # "common" in every doc -> a real hot-strip row (df = N)
        text = "common " + " ".join(WORDS[(i + j) % len(WORDS)]
                                    for j in range(3 + i % 7))
        body.append(f"<DOC>\n<DOCNO> D-{i:04d} </DOCNO>\n<TEXT>\n"
                    f"{text}\n</TEXT>\n</DOC>\n")
    corpus = tmp / "corpus.trec"
    corpus.write_text("".join(body))
    out = str(tmp / "idx")
    build_index([str(corpus)], out, num_shards=3,
                compute_chargrams=False)
    return out


@pytest.fixture(scope="module")
def scorers(index_dir):
    out = {
        "dense": Scorer.load(index_dir, layout="dense"),
        "sparse": Scorer.load(index_dir, layout="sparse"),
        "sharded": Scorer.load(index_dir, layout="sharded"),
    }
    hr = np.asarray(out["sparse"].hot_rank)
    assert (hr >= 0).sum() >= 1, "fixture must have a non-empty hot strip"
    return out


def _check_hits(scorer, res, texts, *, expect_explained: int) -> int:
    """The parity core: every explained hit's contribution sum equals
    the reported score bit-exactly, and explain order IS result order
    (tie-breaks included)."""
    checked = 0
    for r, text in zip(res, texts):
        assert r.explain is not None or not r
        for (key, score), e in zip(r, r.explain or []):
            assert e["contribution_sum"] == e["score"] == score, (
                text, key, e["score"], e["contribution_sum"], score)
            assert scorer.mapping.get_docno(key) == e["docno"]
            assert len(e["terms"]) == e["terms"][-1]["slot"] + 1 \
                if e["terms"] else True
            checked += 1
    assert checked >= expect_explained
    return checked


@pytest.mark.parametrize("layout", ["dense", "sparse", "sharded"])
@pytest.mark.parametrize("scoring", ["tfidf", "bm25"])
def test_explain_sums_bit_exact_per_layout_and_scoring(
        scorers, layout, scoring):
    s = scorers[layout]
    res = s.search_batch(QUERIES, k=5, scoring=scoring, explain_k=3)
    _check_hits(s, res, QUERIES, expect_explained=8)


def test_explain_compat_int_idf(index_dir):
    s = Scorer.load(index_dir, layout="sparse", compat_int_idf=True)
    res = s.search_batch(QUERIES[:3], k=5, explain_k=2)
    _check_hits(s, res, QUERIES[:3], expect_explained=4)


@pytest.mark.parametrize("layout", ["sparse", "sharded"])
def test_explain_hot_only_variant(scorers, layout):
    """The overload ladder's cheapest level: only the hot strip scores,
    and the decomposition must still reproduce those partial scores
    bit-exactly (bm25 — the hot term's tfidf idf is 0 at df == N)."""
    s = scorers[layout]
    res = s.search_batch(["common salmon"], k=5, scoring="bm25",
                         hot_only=True, explain_k=3)
    n = _check_hits(s, res, ["common salmon"], expect_explained=1)
    assert n >= 1
    e = res[0].explain[0]
    assert e["dispatch"]["hot_only"] is True
    # only the hot term contributes at this level
    by_term = {t["term"]: t for t in e["terms"]}
    assert by_term["common"]["placement"] == "hot" or \
        by_term["common"].get("shard") is not None
    assert by_term["salmon"]["contribution"] == 0.0


def test_explain_scheduled_static_skip_path(scorers):
    """The NOTES round-5 production MaxScore specialization: a hot-free
    query is dispatched on the STATIC skip_hot kernel; explain must
    follow it there (same flags, same floats) and say so."""
    s = scorers["sparse"]
    res = s.search_batch(["salmon fishing river"], k=5, scoring="bm25",
                         explain_k=2)
    e = res[0].explain[0]
    assert e["dispatch"]["prune_scheduling"] is True
    assert e["dispatch"]["has_hot_terms"] is False
    assert e["dispatch"]["skip_hot"] is True
    _check_hits(s, res, ["x"], expect_explained=2)
    # and the mixed query takes the full kernel
    res2 = s.search_batch(["common salmon"], k=5, scoring="bm25",
                          explain_k=1)
    assert res2[0].explain[0]["dispatch"]["skip_hot"] is False


@pytest.mark.parametrize("layout", ["dense", "sparse", "sharded"])
def test_explain_rerank_decomposes_cosine_stage(scorers, layout):
    """Two-stage retrieval: the reported score is the cosine stage's —
    explain decomposes THAT bit-exactly and carries the stage-1 BM25
    score + delta."""
    s = scorers[layout]
    res = s.search_batch(["salmon fishing"], k=5, rerank=25,
                         explain_k=3)
    n = _check_hits(s, res, ["salmon fishing"], expect_explained=2)
    assert n >= 2
    for e in res[0].explain:
        rr = e["rerank"]
        assert rr["in_candidates"] is True and rr["candidates"] == 25
        assert rr["stage1_score"] > 0
        # delta is exact in float64 over the two f32 stage scores
        assert np.float64(rr["stage1_score"]) + np.float64(rr["delta"]) \
            == pytest.approx(np.float64(e["score"]), abs=0)


def test_explain_metadata_fields(scorers):
    """tf/df/idf/length-norm/placement ride along and are consistent
    with the host arrays."""
    s = scorers["sparse"]
    e = s.search_batch(["common salmon"], k=3, scoring="bm25",
                       explain_k=1)[0].explain[0]
    assert e["k1"] == 0.9 and e["b"] == 0.4
    assert e["doc_len"] > 0 and e["avg_doc_len"] > 0
    assert 0 < e["dl_norm"] < 3
    df_host = np.asarray(s.df)
    for t in e["terms"]:
        assert t["df"] == int(df_host[t["term_id"]])
        assert t["tf"] >= 1  # every explained hit matched both terms
    by_term = {t["term"]: t for t in e["terms"]}
    assert by_term["common"]["placement"] == "hot"
    assert by_term["common"]["df"] == s.meta.num_docs
    assert by_term["salmon"]["placement"].startswith("tier:")


def test_explain_public_api_and_edge_cases(scorers):
    s = scorers["sparse"]
    res = s.search(
        "honey bears", k=1, scoring="bm25")
    key = res[0][0]
    e = s.explain("honey bears", key, scoring="bm25")
    assert e["docid"] == key
    assert e["contribution_sum"] == e["score"] == res[0][1]

    # unknown-terms-only query: empty decomposition, score 0
    e0 = explain_hits(s, "zzznope qqqnope", [1], scoring="bm25")[0]
    assert e0["terms"] == [] and e0["score"] == 0.0
    assert e0["contribution_sum"] == 0.0

    # out-of-range docno: structured error entry, no crash
    bad = explain_hits(s, "honey", [10 ** 6], scoring="bm25")[0]
    assert "error" in bad

    # rerank explain of a doc outside the candidate set is tagged
    cand_out = explain_hits(s, "honey bears",
                            [s.meta.num_docs], rerank=5)
    assert cand_out[0]["rerank"]["in_candidates"] in (True, False)


def test_degraded_results_carry_no_explain(scorers):
    import tpu_ir.faults as faults

    s = scorers["sparse"]
    faults.install(faults.parse_plan("score.device_loss:first@1"))
    try:
        res = s.search_batch(["honey bears"], k=3, scoring="bm25",
                             deadline_s=5.0, explain_k=2)
    finally:
        faults.clear()
    assert res[0].degraded
    assert res[0].explain is None


# ---------------------------------------------------------------------------
# the runtime-prune variant (ops-level: production never passes prune=True,
# so the parity pin runs against the kernels directly, engagement proven
# via the diag — the test_maxscore fixture technique)
# ---------------------------------------------------------------------------


from tpu_ir.ops.scoring import (  # noqa: E402
    MAXSCORE_CAND,
    bm25_scores_at_tiered,
    bm25_topk_tiered,
    tfidf_prune_diag,
    tfidf_scores_at_tiered,
    tfidf_topk_tiered,
)
from tpu_ir.search.layout import build_tiered_layout  # noqa: E402

NDOCS = 2 * MAXSCORE_CAND + 500


def _zipf_pairs(vocab=2000, ndocs=NDOCS, n_occ=90_000, seed=5):
    rng = np.random.default_rng(seed)
    p = 1.0 / np.arange(1, vocab + 1)
    p /= p.sum()
    t = rng.choice(vocab, n_occ, p=p).astype(np.int64)
    d = rng.integers(1, ndocs + 1, n_occ).astype(np.int64)
    key, tf = np.unique(t * (ndocs + 1) + d, return_counts=True)
    pair_doc = (key % (ndocs + 1)).astype(np.int32)
    pair_tf = tf.astype(np.int32)
    df = np.bincount((key // (ndocs + 1)).astype(np.int32),
                     minlength=vocab).astype(np.int32)
    return pair_doc, pair_tf, df


@pytest.fixture(scope="module")
def prune_layout():
    pair_doc, pair_tf, df = _zipf_pairs()
    lay = build_tiered_layout(pair_doc, pair_tf, df, num_docs=NDOCS,
                              hot_budget=24 * (NDOCS + 1))
    args = (jnp.asarray(lay.hot_rank), lay.hot_device(),
            jnp.asarray(lay.tier_of), jnp.asarray(lay.row_of),
            tuple(jnp.asarray(a) for a in lay.tier_docs),
            tuple(jnp.asarray(a) for a in lay.tier_tfs))
    hot_max_tf = jnp.max(args[1], axis=1)
    return df, lay, args, hot_max_tf


def _safe_queries(df, lay, seed=11):
    hot = np.nonzero(lay.hot_rank >= 0)[0]
    hottest = int(hot[np.argmax(df[hot])])
    cold_mid = np.nonzero((lay.hot_rank < 0) & (df >= 30)
                          & (df <= 200))[0]
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(8):
        if i % 2 == 0:
            rows.append([int(rng.choice(cold_mid)),
                         int(rng.choice(cold_mid)), -1])
        else:
            rows.append([hottest, int(rng.choice(cold_mid)),
                         int(rng.choice(cold_mid))])
    return np.array(rows, np.int32)


@pytest.mark.parametrize("scoring", ["tfidf", "bm25"])
def test_prune_variant_gather_and_telescope_bit_exact(
        prune_layout, scoring):
    """With the pruned branch PROVABLY engaged (diag-certified), the
    explain gather variant must return the pruned kernel's exact floats
    for the returned docs, and the prefix-telescoped contributions must
    sum to them bit-exactly."""
    df, lay, args, hot_max_tf = prune_layout
    q = _safe_queries(df, lay)
    dfj, n = jnp.asarray(df), jnp.int32(NDOCS)
    safe = np.asarray(tfidf_prune_diag(
        jnp.asarray(q), *args, dfj, n, hot_max_tf, num_docs=NDOCS, k=10))
    assert safe.all(), "constructed-safe batch must engage pruning"

    if scoring == "tfidf":
        s1, d1 = tfidf_topk_tiered(jnp.asarray(q), *args, dfj, n,
                                   hot_max_tf, num_docs=NDOCS, k=10,
                                   prune=True)
        got = tfidf_scores_at_tiered(jnp.asarray(q), *args, dfj, n, d1,
                                     hot_max_tf, num_docs=NDOCS,
                                     prune_k=10, prune=True)
    else:
        dl = jnp.asarray(
            np.random.default_rng(0).integers(
                5, 50, NDOCS + 1).astype(np.int32))
        s1, d1 = bm25_topk_tiered(jnp.asarray(q), *args, dfj, dl, n,
                                  hot_max_tf, num_docs=NDOCS, k=10,
                                  prune=True)
        got = bm25_scores_at_tiered(jnp.asarray(q), *args, dfj, dl, n,
                                    d1, hot_max_tf, num_docs=NDOCS,
                                    prune_k=10, prune=True)
    s1, d1, got = np.asarray(s1), np.asarray(d1), np.asarray(got)
    valid = d1 > 0
    assert valid.any()
    np.testing.assert_array_equal(got[valid], s1[valid])

    # telescoped per-slot contributions on the pruned kernel: prefix
    # rows of the first query, gathered at its top doc
    qi = 0
    ids = [int(t) for t in q[qi] if t >= 0]
    qp = np.full((len(ids) + 1, q.shape[1]), -1, np.int32)
    for j in range(1, len(ids) + 1):
        qp[j, :j] = ids[:j]
    cand = np.tile(d1[qi : qi + 1, :1], (len(qp), 1))
    if scoring == "tfidf":
        prefix = np.asarray(tfidf_scores_at_tiered(
            jnp.asarray(qp), *args, dfj, n, jnp.asarray(cand),
            hot_max_tf, num_docs=NDOCS, prune_k=10, prune=True))
    else:
        prefix = np.asarray(bm25_scores_at_tiered(
            jnp.asarray(qp), *args, dfj, dl, n, jnp.asarray(cand),
            hot_max_tf, num_docs=NDOCS, prune_k=10, prune=True))
    col = prefix[:, 0].astype(np.float64)
    contribs = [col[j] - col[j - 1] for j in range(1, len(ids) + 1)]
    assert float(np.sum(contribs)) == float(s1[qi, 0])
