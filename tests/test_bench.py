"""The bench configs' eval machinery, at toy scale on CPU.

Guards the planted-relevance corpus generator and the MRR computation that
back `bench.py --config msmarco` (BASELINE.json's quality metric), and that
BM25 actually ranks the two-term relevant passage above the single-term
high-tf distractors it plants.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def test_msmarco_planted_relevance_mrr(tmp_path):
    import bench
    from tpu_ir.index import build_index
    from tpu_ir.search import Scorer

    corpus = str(tmp_path / "c.trec")
    queries, rel = bench.make_msmarco_corpus(corpus, n_docs=300,
                                             n_queries=20)
    assert len(queries) == 20 and rel.min() >= 1 and rel.max() <= 300
    idx = str(tmp_path / "idx")
    build_index([corpus], idx, k=1, chargram_ks=[], num_shards=3,
                compute_chargrams=False)
    scorer = Scorer.load(idx, layout="dense")
    q = scorer.analyze_queries(queries, max_terms=4)
    _, docnos = scorer.topk(q, k=10, scoring="bm25")
    assert bench._mrr_at_k(rel, docnos) == 1.0

    # tf-idf with raw tf (no saturation) must still find the doc in top-10
    _, d2 = scorer.topk(q, k=10, scoring="tfidf")
    assert bench._mrr_at_k(rel, d2) > 0.5


def test_mrr_at_k():
    import bench

    rel = np.array([5, 7, 9])
    got = np.array([[5, 1, 2], [1, 7, 3], [0, 0, 0]])
    assert bench._mrr_at_k(rel, got) == round((1.0 + 0.5 + 0.0) / 3, 4)
