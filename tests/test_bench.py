"""The bench configs' eval machinery, at toy scale on CPU.

Guards the graded planted-relevance generator, MRR/NDCG computation and the
quality_gate that back `bench.py --config msmarco`: the corpus must SPLIT
the scorers (rerank > BM25 > TF-IDF, all strictly inside (0, 1)) — the
round-1 generator saturated every scorer at MRR 1.0 and could not detect a
regression — and a deliberately broken idf must fail the gate.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture(scope="module")
def quality_setup(tmp_path_factory):
    import bench
    from tpu_ir.index import build_index
    from tpu_ir.search import Scorer

    tmp = tmp_path_factory.mktemp("bench")
    corpus = str(tmp / "c.trec")
    # n_queries divisible by 4 so every query TYPE (qi % 4) is equally
    # represented — the gate's margins assume the balanced mix
    queries, rel, grades = bench.make_quality_corpus(corpus, n_docs=600,
                                                     n_queries=60)
    assert len(queries) == 60 and rel.min() >= 1 and rel.max() <= 600
    idx = str(tmp / "idx")
    build_index([corpus], idx, k=1, chargram_ks=[], num_shards=3,
                compute_chargrams=False)
    scorer = Scorer.load(idx, layout="dense")
    q = scorer.analyze_queries(queries, max_terms=4)
    return bench, scorer, q, rel, grades


def _metrics(bench, scorer, q, rel, grades):
    out = {}
    for scoring in ("tfidf", "bm25"):
        _, d = scorer.topk(q, k=10, scoring=scoring)
        out[f"{scoring}_mrr_at_10"] = bench._mrr_at_k(rel, d)
        out[f"{scoring}_ndcg_at_10"] = bench._ndcg_at_k(grades, d)
    _, d = scorer.rerank_topk(q, k=10, candidates=50)
    out["rerank_mrr_at_10"] = bench._mrr_at_k(rel, d)
    out["rerank_ndcg_at_10"] = bench._ndcg_at_k(grades, d)
    return out


def test_quality_corpus_splits_the_scorers(quality_setup):
    bench, scorer, q, rel, grades = quality_setup
    m = _metrics(bench, scorer, q, rel, grades)
    assert bench.quality_gate(m) == [], m
    # the intended mechanism, not just the ordering: verbose docs fool
    # length-blind TF-IDF, ties cost BM25, type-2 caps everyone < 1
    assert m["tfidf_mrr_at_10"] < 0.75
    assert m["rerank_mrr_at_10"] < 1.0


def test_broken_idf_fails_the_gate(quality_setup, monkeypatch):
    """A scoring regression must be DETECTED: flatten idf to a constant
    (df ignored) and the gate has to report violations (the idf-canary
    queries collapse TF-IDF and the rerank while BM25, which computes its
    own idf, stands — breaking the required ordering)."""
    import jax.numpy as jnp

    import tpu_ir.ops
    import tpu_ir.ops.scoring as scoring_mod
    from tpu_ir.search import Scorer

    bench, scorer, q, rel, grades = quality_setup

    def flat_idf(df, n, compat_int_idf=False):
        return jnp.ones(df.shape, jnp.float32)

    monkeypatch.setattr(scoring_mod, "idf_weights", flat_idf)
    monkeypatch.setattr(tpu_ir.ops, "idf_weights", flat_idf)
    # the jitted scorers captured the healthy idf_weights at trace time and
    # their caches key on shapes — drop them so the patch actually traces
    scoring_mod.tfidf_topk_dense.clear_cache()
    scoring_mod.cosine_rerank_dense.clear_cache()
    try:
        broken = Scorer.load(scorer._index_dir, layout="dense")
        m = _metrics(bench, broken, q, rel, grades)
        assert bench.quality_gate(m) != [], m
    finally:
        monkeypatch.undo()
        scoring_mod.tfidf_topk_dense.clear_cache()
        scoring_mod.cosine_rerank_dense.clear_cache()


def test_mrr_at_k():
    import bench

    rel = np.array([5, 7, 9])
    got = np.array([[5, 1, 2], [1, 7, 3], [0, 0, 0]])
    assert bench._mrr_at_k(rel, got) == round((1.0 + 0.5 + 0.0) / 3, 4)


def test_ndcg_at_k():
    import bench

    grades = [{1: 2, 2: 1}, {3: 2}]
    got = np.array([[2, 1, 0], [9, 8, 7]])
    # query 1: dcg = 1/log2(2) + 3/log2(3); idcg = 3/log2(2) + 1/log2(3)
    q1 = (1.0 + 3 / np.log2(3)) / (3.0 + 1 / np.log2(3))
    assert bench._ndcg_at_k(grades, got) == round((q1 + 0.0) / 2, 4)


def test_eval_loop_roundtrip(tmp_path):
    """The bench's topics -> CLI --trec-run -> evaluate_run loop must
    reproduce in-process BM25 metrics exactly, and flag any divergence."""
    import bench
    from tpu_ir.index import build_index
    from tpu_ir.search import Scorer

    corpus = str(tmp_path / "c.trec")
    queries, rel, grades = bench.make_quality_corpus(
        corpus, n_docs=400, n_queries=24)
    idx = str(tmp_path / "idx")
    build_index([corpus], idx, k=1, chargram_ks=[], num_shards=3,
                compute_chargrams=False)
    scorer = Scorer.load(idx, layout="dense")
    q = scorer.analyze_queries(queries, max_terms=4)
    _, d10 = scorer.topk(q, k=10, scoring="bm25")

    out = bench._eval_loop_roundtrip(str(tmp_path), idx, queries, grades,
                                     d10)
    assert out["eval_loop"] == "ok", out
    assert out["eval_loop_queries"] == 24
    assert 0 < out["eval_loop_mrr"] <= 1

    # a diverging in-process ranking must be flagged, not silently passed
    bad = bench._eval_loop_roundtrip(str(tmp_path), idx, queries, grades,
                                     np.zeros_like(d10))
    assert bad["eval_loop"].startswith("mismatch")


def test_prox_tie_pairs_need_the_boost(tmp_path):
    """The prox-tie pairs tie every bag-of-words scorer exactly (tie
    rigged toward the distractor); the positions-based boost flips them
    to the relevant doc — the measured lift the msmarco bench asserts."""
    import bench
    from tpu_ir.index import build_index
    from tpu_ir.search import Scorer

    corpus = str(tmp_path / "c.trec")
    out = bench.make_quality_corpus(corpus, n_docs=500, n_queries=24,
                                    with_prox=True)
    queries, rel, grades, (prox_q, prox_rel) = out
    assert len(prox_q) == 6
    idx = str(tmp_path / "idx")
    build_index([corpus], idx, k=1, chargram_ks=[], num_shards=3,
                compute_chargrams=False, positions=True)
    scorer = Scorer.load(idx, layout="dense")

    def subset_mrr(results):
        got = np.array(
            [[dn for dn, _ in r[:10]] + [0] * (10 - min(len(r), 10))
             for r in results], np.int64)
        return bench._mrr_at_k(prox_rel, got)

    base = subset_mrr(scorer.search_batch(prox_q, k=10, rerank=50,
                                          return_docids=False))
    boosted = subset_mrr(scorer.search_batch(prox_q, k=10, rerank=50,
                                             prox=True,
                                             return_docids=False))
    assert base == pytest.approx(0.5)   # exact ties, distractor first
    assert boosted == pytest.approx(1.0)
    m = {"rerank_mrr_prox_subset": base,
         "prox_rerank_mrr_prox_subset": boosted}
    # the gate clause fires on a broken boost
    m_bad = dict(m, prox_rerank_mrr_prox_subset=base)
    assert any("proximity" in b for b in _prox_gate(m_bad))
    assert not _prox_gate(m)


def _prox_gate(m):
    """Just the prox clause of bench.quality_gate."""
    full = {"tfidf_mrr_at_10": 0.5, "bm25_mrr_at_10": 0.6,
            "rerank_mrr_at_10": 0.7, "tfidf_ndcg_at_10": 0.5,
            "bm25_ndcg_at_10": 0.6, "rerank_ndcg_at_10": 0.7, **m}
    import bench
    return bench.quality_gate(full)
