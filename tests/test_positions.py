"""Format v2 positions: per-posting position runs aligned with the part
files (VERDICT r2 item 4). The reference format carries only (docno, tf)
(PostingWritable.java:9-65); v2 keeps the token coordinates the analyzer
already computes, enabling phrase and proximity retrieval."""

import os

import numpy as np
import pytest

from tpu_ir.analysis import Analyzer
from tpu_ir.index import build_index
from tpu_ir.index import format as fmt
from tpu_ir.index.positions import PositionsReader, positions_name

DOCS = {
    "P-01": "salmon fishing in the river salmon fishing again",
    "P-02": "fishing salmon is not salmon fishing",
    "P-03": "the quick brown fox jumps over the lazy dog",
    "P-04": "river fishing river fishing river fishing",
    "P-05": "salmon salmon salmon fishing",
}


def corpus_file(tmp_path):
    p = tmp_path / "corpus.trec"
    p.write_text("".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in DOCS.items()))
    return str(p)


def oracle_positions():
    """docid -> term -> ascending post-analysis token positions."""
    an = Analyzer()
    out = {}
    for d, t in DOCS.items():
        toks = an.analyze(
            f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>")
        per = {}
        for i, tok in enumerate(toks):
            per.setdefault(tok, []).append(i)
        out[d] = per
    return out


@pytest.mark.parametrize("spmd", [None, 8])
def test_positions_match_oracle(tmp_path, spmd):
    """Every pair row's decoded position run equals the analyzer's token
    coordinates for that (term, doc) — on the single-device and the SPMD
    build — and run lengths equal the pair's tf."""
    out = str(tmp_path / f"idx-{spmd}")
    meta = build_index([corpus_file(tmp_path)], out, k=1,
                       num_shards=3 if spmd is None else spmd,
                       compute_chargrams=False, positions=True,
                       spmd_devices=spmd)
    assert meta.has_positions and meta.version == 2

    from tpu_ir.collection import DocnoMapping, Vocab

    vocab = Vocab.load(os.path.join(out, fmt.VOCAB))
    mapping = DocnoMapping.load(os.path.join(out, fmt.DOCNOS))
    want = oracle_positions()
    reader = PositionsReader(out)
    assert reader.available()

    n_checked = 0
    for s in range(meta.num_shards):
        z = fmt.load_shard(out, s)
        runs = reader.runs_for_rows(s, 0, len(z["pair_doc"]))
        row = 0
        for i, tid in enumerate(z["term_ids"]):
            term = vocab.terms[int(tid)]
            for r in range(int(z["indptr"][i]), int(z["indptr"][i + 1])):
                docno = int(z["pair_doc"][r])
                tf = int(z["pair_tf"][r])
                docid = mapping.get_docid(docno)
                got = runs[r].tolist()
                assert len(got) == tf, (term, docid)
                assert got == want[docid][term], (term, docid)
                n_checked += 1
            row += 1
    assert n_checked == meta.num_pairs


def test_v1_index_loads_without_positions(tmp_path):
    out = str(tmp_path / "idx")
    meta = build_index([corpus_file(tmp_path)], out, k=1, num_shards=2,
                       compute_chargrams=False)
    assert not meta.has_positions and meta.version == 1
    assert not os.path.exists(os.path.join(out, positions_name(0)))
    assert not PositionsReader(out).available()
    # and an old metadata.json without the key still loads
    import json
    mp = os.path.join(out, fmt.METADATA)
    with open(mp) as f:
        m = json.load(f)
    del m["has_positions"]
    with open(mp, "w") as f:
        json.dump(m, f)
    assert fmt.IndexMetadata.load(out).has_positions is False


def test_positions_kgram_index(tmp_path):
    """k=2 index: a gram's position is its window start, so adjacency
    carries through composed terms too."""
    out = str(tmp_path / "idx2")
    meta = build_index([corpus_file(tmp_path)], out, k=2, num_shards=2,
                       compute_chargrams=False, positions=True)
    assert meta.has_positions

    from tpu_ir.collection import Vocab, kgram_terms
    from tpu_ir.index.dictionary import lookup_term

    an = Analyzer()
    record = (f"<DOC>\n<DOCNO> P-04 </DOCNO>\n<TEXT>\n{DOCS['P-04']}\n"
              f"</TEXT>\n</DOC>")
    grams = kgram_terms(an.analyze(record), 2)
    target = next(g for g in grams
                  if g.startswith("river") and "fish" in g)  # 'river fish'
    want_pos = [i for i, g in enumerate(grams) if g == target]
    assert len(want_pos) == 3  # river-fishing repeats three times
    vocab = Vocab.load(os.path.join(out, fmt.VOCAB))
    tid = vocab.terms.index(target)
    shard = tid % meta.num_shards
    z = fmt.load_shard(out, shard)
    i = int(np.searchsorted(z["term_ids"], tid))
    reader = PositionsReader(out)
    rows = reader.runs_for_rows(shard, int(z["indptr"][i]),
                                int(z["indptr"][i + 1]))
    by_doc = {int(z["pair_doc"][r]): rows[j] for j, r in enumerate(
        range(int(z["indptr"][i]), int(z["indptr"][i + 1])))}
    # P-04 is docno of "P-04"
    from tpu_ir.collection import DocnoMapping
    mapping = DocnoMapping.load(os.path.join(out, fmt.DOCNOS))
    docno = mapping.get_docno("P-04")
    assert by_doc[docno].tolist() == want_pos


PHRASE_DOCS = {
    # 'salmon fishing' adjacent
    "F-01": "salmon fishing is fun and salmon are tasty",
    # both words, NOT adjacent, wrong order nearby
    "F-02": "fishing for trout while salmon swim upstream",
    # adjacent but reversed
    "F-03": "fishing salmon is a different phrase entirely",
    # adjacent twice (higher tf)
    "F-04": "salmon fishing and more salmon fishing all day",
    # one-word gap: matches only at slop >= 1
    "F-05": "salmon net fishing with a big net",
    # neither word adjacent, scattered far apart
    "F-06": "salmon swim far away from any fishing boats here today",
    # fillers WITHOUT the phrase terms, so their idf stays positive
    "F-07": "quick brown fox jumps over lazy dog tonight",
    "F-08": "stock markets fell sharply as investors fled",
}


@pytest.fixture(scope="module")
def phrase_index(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("phrase")
    p = tmp / "corpus.trec"
    p.write_text("".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in PHRASE_DOCS.items()))
    out = str(tmp / "idx")
    build_index([str(p)], out, k=1, num_shards=3, compute_chargrams=False,
                positions=True)
    return out


def test_phrase_query_exact_adjacency(phrase_index):
    """A quoted phrase returns ONLY true ordered-adjacency matches: not
    reversed pairs, not co-occurrence, not gapped spans."""
    from tpu_ir.search import Scorer

    scorer = Scorer.load(phrase_index)
    got = {d for d, _ in scorer.search('"salmon fishing"')}
    assert got == {"F-01", "F-04"}
    # reversed phrase: F-03 has it literally; F-04 gains it because
    # positions are POST-analysis coordinates — the stopwords in
    # "fishing and more salmon" vanish at analysis, making fish/salmon
    # adjacent (standard for positional indexes built after analysis)
    got_rev = {d for d, _ in scorer.search('"fishing salmon"')}
    assert got_rev == {"F-03", "F-04"}
    # slop=1 admits the one-gap doc too
    got_slop = {d for d, _ in scorer.search('"salmon fishing"',
                                            phrase_slop=1)}
    assert got_slop == {"F-01", "F-04", "F-05"}
    # no match -> empty, not a crash
    assert scorer.search('"tasty trout"') == []
    # phrase + free terms: phrase filters, all terms score
    got_mixed = {d for d, _ in scorer.search('"salmon fishing" fun')}
    assert got_mixed == {"F-01", "F-04"}
    # ranking holds: doc with the phrase twice + 'fun' absent vs doc with
    # phrase once + 'fun' present — just assert both rank and scores > 0
    res = scorer.search('"salmon fishing"', scoring="bm25")
    assert {d for d, _ in res} == {"F-01", "F-04"}
    assert all(s > 0 for _, s in res)


def test_phrase_query_batch_mixed(phrase_index):
    """search_batch interleaves phrase and plain queries preserving
    order; plain queries still ride the device batch path."""
    from tpu_ir.search import Scorer

    scorer = Scorer.load(phrase_index)
    res = scorer.search_batch(
        ['salmon', '"salmon fishing"', 'fishing boats', '"fishing salmon"'])
    assert {d for d, _ in res[1]} == {"F-01", "F-04"}
    assert {d for d, _ in res[3]} == {"F-03", "F-04"}
    # plain queries equal their individually-searched selves
    assert res[0] == scorer.search("salmon")
    assert res[2] == scorer.search("fishing boats")


def test_match_window_random_oracle(tmp_path):
    """The vectorized all-candidates chain (doc_rank*M+pos keys, one
    searchsorted per term) must agree with a scalar greedy oracle on a
    random corpus — every (terms, slop) combination, including repeated
    terms and absent terms."""
    import random

    from tpu_ir.analysis.native import make_analyzer
    from tpu_ir.search import Scorer
    from tpu_ir.search.phrase import PhraseIndex

    rng = random.Random(13)
    vocab = ["alpha", "beta", "gamma", "delta", "epsilon",
             "zeta", "theta", "kappa"]
    docs = {f"R-{i:03d}": " ".join(rng.choice(vocab)
                                   for _ in range(rng.randint(4, 28)))
            for i in range(80)}
    p = tmp_path / "c.trec"
    p.write_text("".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in docs.items()))
    out = str(tmp_path / "idx")
    build_index([str(p)], out, k=1, num_shards=3, compute_chargrams=False,
                positions=True)
    scorer = Scorer.load(out)
    pidx = PhraseIndex(out)
    analyzer = make_analyzer()
    toks = {scorer.mapping.get_docno(d): analyzer.analyze(t)
            for d, t in docs.items()}

    def oracle(terms, slop):
        # greedy chains from every start are optimal for ordered windows
        span = len(terms) - 1 + slop
        hits = []
        for dn, tk in toks.items():
            pos = {t: [i for i, x in enumerate(tk) if x == t]
                   for t in set(terms)}
            for p0 in pos.get(terms[0], []):
                cur, ok = p0, True
                for t in terms[1:]:
                    nxt = [q for q in pos.get(t, []) if q > cur]
                    if not nxt:
                        ok = False
                        break
                    cur = nxt[0]
                if ok and cur - p0 <= span:
                    hits.append(dn)
                    break
        return sorted(hits)

    cases = [(["alpha", "beta"], 0), (["alpha", "beta"], 1),
             (["beta", "alpha"], 0), (["gamma", "gamma"], 0),
             (["alpha", "beta", "gamma"], 0),
             (["alpha", "beta", "gamma"], 2),
             (["delta", "epsilon", "zeta", "theta"], 3),
             (["alpha"], 0), (["alpha", "missing"], 0)]
    for _ in range(12):
        m = rng.randint(2, 4)
        cases.append(([rng.choice(vocab) for _ in range(m)],
                      rng.randint(0, 3)))
    for terms, slop in cases:
        got = sorted(pidx.match_window(terms, slop=slop))
        assert got == oracle(terms, slop), (terms, slop)


def test_high_df_phrase_no_scalar_decode(tmp_path, monkeypatch):
    """A phrase of two corpus-wide terms (df == N) must stay on the bulk
    gather path: the scalar per-run decoder is forbidden during matching,
    and the whole query meets a generous wall-clock budget. This is the
    guardrail against the round-3 per-doc Python loop regressing back."""
    import time

    from tpu_ir.index.positions import PositionsReader
    from tpu_ir.search import Scorer

    n = 1500
    p = tmp_path / "c.trec"
    # every doc holds both terms; only half adjacent in order
    p.write_text("".join(
        "<DOC>\n<DOCNO> H-%04d </DOCNO>\n<TEXT>\n%s\n</TEXT>\n</DOC>\n"
        % (i, ("new york pizza parlor" if i % 2
               else "york visited new friends"))
        for i in range(n)))
    out = str(tmp_path / "idx")
    build_index([str(p)], out, k=1, num_shards=2, compute_chargrams=False,
                positions=True)
    scorer = Scorer.load(out)
    monkeypatch.setattr(
        PositionsReader, "run",
        lambda *a, **kw: (_ for _ in ()).throw(AssertionError(
            "match_window must use the bulk decode path, not per-run")))
    t0 = time.monotonic()
    res = scorer.search('"new york"', k=5, scoring="bm25")
    elapsed = time.monotonic() - t0
    assert len(res) == 5
    assert all(d.startswith("H-") and int(d[2:]) % 2 == 1 for d, _ in res)
    assert elapsed < 5.0, f"high-df phrase took {elapsed:.2f}s"


def test_phrase_rerank_prox_compose(phrase_index):
    """--rerank/--prox thread through quoted queries (VERDICT r3 weak 3):
    the matched docs are BM25-selected then cosine-rescored with the SAME
    model as the plain path, and --prox boosts adjacency on top."""
    import numpy as np

    from tpu_ir.search import Scorer
    from tpu_ir.search.phrase import PhraseIndex, cosine_score_host

    scorer = Scorer.load(phrase_index)
    res = scorer.search('"salmon fishing"', rerank=10)
    assert {d for d, _ in res} == {"F-01", "F-04"}
    # scores equal the host cosine twin over exactly the matched docs
    pidx = PhraseIndex(phrase_index)
    matched = sorted(scorer.mapping.get_docno(d) for d in ("F-01", "F-04"))
    docnos, want = cosine_score_host(
        scorer._query_term_sequence("salmon fishing"), matched,
        dictionary=pidx._dict, num_docs=scorer.meta.num_docs,
        doc_norms=scorer._doc_norms_host())
    want_by_doc = {scorer.mapping.get_docid(int(d)): float(s)
                   for d, s in zip(docnos, want)}
    for d, s in res:
        assert s == pytest.approx(want_by_doc[d], rel=1e-5)
    # prox composes: multiplicative boost, same doc set, F-04 (phrase
    # twice, tighter windows) still leads
    boosted = scorer.search('"salmon fishing"', rerank=10, prox=True)
    assert {d for d, _ in boosted} == {"F-01", "F-04"}
    assert dict(boosted)["F-01"] >= dict(res)["F-01"]
    # batch mixing quoted and plain queries: one pipeline for both
    batch = scorer.search_batch(['"salmon fishing"', "salmon fishing"],
                                rerank=10, prox=True)
    assert batch[0] == boosted
    assert batch[1] == scorer.search("salmon fishing", rerank=10,
                                     prox=True)


def test_stray_quote_keeps_rerank(phrase_index):
    """A stray/unmatched quote routes through the no-phrase fallback,
    which must preserve the caller's rerank/prox pipeline (ADVICE r3) —
    identical results to the same query without the quote."""
    from tpu_ir.search import Scorer

    scorer = Scorer.load(phrase_index)
    for kw in (dict(rerank=6), dict(rerank=6, prox=True)):
        assert (scorer.search('salmon" fishing', **kw)
                == scorer.search("salmon fishing", **kw)), kw


def test_phrase_caches_bounded(phrase_index):
    """Long-lived serving: the per-(term, doc) run cache and the term
    postings cache evict LRU instead of growing without bound."""
    from tpu_ir.search.phrase import PhraseIndex

    pidx = PhraseIndex(phrase_index)
    pidx.POS_CACHE_CAP = 4
    pidx.TERM_CACHE_CAP = 3
    dns = [pidx.doc_set("salmon")[i] for i in range(3)]
    for t in ("salmon", "fishing"):
        for dn in dns:
            pidx.positions(t, int(dn))
    assert len(pidx._pos_cache) <= 4
    for t in ("salmon", "fishing", "fun", "trout", "boats"):
        pidx._term(t)
    assert len(pidx._term_cache) <= 3
    # eviction is correctness-neutral: a re-query decodes again
    p = pidx.positions("salmon", int(dns[0]))
    assert p is not None and len(p) > 0


def test_phrase_requires_positions(tmp_path):
    """v1 index (no positions): quoted query raises the documented error
    instead of silently degrading."""
    from tpu_ir.search import Scorer

    p = tmp_path / "c.trec"
    p.write_text("<DOC>\n<DOCNO> X </DOCNO>\n<TEXT>\nsalmon fishing\n"
                 "</TEXT>\n</DOC>\n")
    out = str(tmp_path / "idx")
    build_index([str(p)], out, k=1, num_shards=2, compute_chargrams=False)
    scorer = Scorer.load(out)
    with pytest.raises(ValueError, match="position"):
        scorer.search('"salmon fishing"')


def test_proximity_rerank_prefers_adjacent(phrase_index):
    """--prox: same bag of words, but the doc where the query terms sit
    adjacent outranks the doc where they are scattered."""
    from tpu_ir.search import Scorer

    scorer = Scorer.load(phrase_index)
    base = scorer.search("salmon fishing", rerank=6)
    boosted = scorer.search("salmon fishing", rerank=6, prox=True)
    assert {d for d, _ in base} == {d for d, _ in boosted}
    rank_b = {d: i for i, (d, _) in enumerate(boosted)}
    # adjacent docs must beat the scattered one after the boost
    assert rank_b["F-01"] < rank_b["F-06"]
    assert rank_b["F-04"] < rank_b["F-06"]
    # the boost is multiplicative and positive
    s_base = dict(base)
    s_boost = dict(boosted)
    assert s_boost["F-01"] > s_base["F-01"]
    # a doc with no co-occurrence proximity keeps its score
    from tpu_ir.search.phrase import PROX_ALPHA, PhraseIndex

    pidx = PhraseIndex(phrase_index)
    docno_f06 = scorer.mapping.get_docno("F-06")
    bonus = pidx.proximity_bonus(
        scorer._query_term_sequence("salmon fishing"), docno_f06)
    assert s_boost["F-06"] == pytest.approx(
        s_base["F-06"] * (1 + PROX_ALPHA * bonus), rel=1e-5)


def test_phrase_kgram_index(tmp_path):
    """Phrase matching composes through a k=2 gram index: consecutive
    gram positions differ by 1."""
    from tpu_ir.search import Scorer

    p = tmp_path / "c.trec"
    p.write_text("".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in {
            "G-1": "big salmon fishing trip today",
            "G-2": "salmon trip and fishing big today",
        }.items()))
    out = str(tmp_path / "idx2")
    build_index([str(p)], out, k=2, num_shards=2, compute_chargrams=False,
                positions=True)
    scorer = Scorer.load(out)
    got = {d for d, _ in scorer.search('"salmon fishing trip"')}
    assert got == {"G-1"}


def test_verify_checks_positions(phrase_index, tmp_path):
    """tpu-ir verify validates position runs (length == tf, ascending,
    inside the doc) and fails loudly on tampered artifacts."""
    import shutil

    from tpu_ir.index.verify import verify_index

    out = verify_index(phrase_index)
    assert out["ok"] and out["has_positions"]

    tampered = str(tmp_path / "tampered")
    shutil.copytree(phrase_index, tampered)
    name = positions_name(0)
    with np.load(os.path.join(tampered, name)) as z:
        indptr, delta = z["pos_indptr"].copy(), z["pos_delta"].copy()
    delta[0] = 10_000  # position way past any doc length
    np.savez(os.path.join(tampered, name), pos_indptr=indptr,
             pos_delta=delta)
    with pytest.raises(AssertionError, match="position"):
        verify_index(tampered)

    # missing file also fails
    os.unlink(os.path.join(tampered, name))
    with pytest.raises(AssertionError, match="missing"):
        verify_index(tampered)


def test_stray_quote_falls_back_to_plain(tmp_path):
    """An unbalanced or empty quote is punctuation, not a phrase: the
    query runs plain — on a v1 index too, where a phrase would error."""
    from tpu_ir.search import Scorer

    p = tmp_path / "c.trec"
    p.write_text("<DOC>\n<DOCNO> X </DOCNO>\n<TEXT>\nrack mount server\n"
                 "</TEXT>\n</DOC>\n")
    out = str(tmp_path / "idx")
    build_index([str(p)], out, k=1, num_shards=2, compute_chargrams=False)
    scorer = Scorer.load(out)  # v1: no positions
    assert scorer.search('19" rack mount') == scorer.search("19 rack mount")
    assert scorer.search('rack ""') == scorer.search("rack")


def test_prox_requires_rerank(phrase_index):
    from tpu_ir.search import Scorer

    scorer = Scorer.load(phrase_index)
    with pytest.raises(ValueError, match="rerank"):
        scorer.search("salmon fishing", prox=True)


def test_merge_preserves_positions(tmp_path):
    """Merging position-built indexes keeps positions, byte-identical to
    a one-shot positions build over the concatenated corpus; a mixed
    v1+v2 merge is rejected loudly."""
    import filecmp

    from tpu_ir.index.merge import merge_indexes
    from tpu_ir.index.verify import verify_index
    from tpu_ir.search import Scorer

    docs_a = {k: v for i, (k, v) in enumerate(PHRASE_DOCS.items())
              if i % 2 == 0}
    docs_b = {k: v for i, (k, v) in enumerate(PHRASE_DOCS.items())
              if i % 2 == 1}

    def write(name, docs):
        p = tmp_path / name
        p.write_text("".join(
            f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
            for d, t in docs.items()))
        return str(p)

    ia, ib = str(tmp_path / "ia"), str(tmp_path / "ib")
    build_index([write("a.trec", docs_a)], ia, k=1, num_shards=2,
                compute_chargrams=False, positions=True)
    build_index([write("b.trec", docs_b)], ib, k=1, num_shards=3,
                compute_chargrams=False, positions=True)
    direct = str(tmp_path / "direct")
    build_index([write("both.trec", PHRASE_DOCS)], direct, k=1,
                num_shards=4, compute_chargrams=False, positions=True)

    merged = str(tmp_path / "merged")
    meta = merge_indexes([ia, ib], merged, num_shards=4,
                         compute_chargrams=False)
    assert meta.has_positions and meta.version == 2
    assert verify_index(merged)["ok"]
    for s in range(4):
        assert filecmp.cmp(os.path.join(direct, positions_name(s)),
                           os.path.join(merged, positions_name(s)),
                           shallow=False), s
    # phrase queries work on the merged index
    got = {d for d, _ in Scorer.load(merged).search('"salmon fishing"')}
    assert got == {"F-01", "F-04"}

    # mixed merge: one v1 source -> loud error
    iv1 = str(tmp_path / "iv1")
    build_index([write("c.trec", {"V1-1": "totally new words"})], iv1,
                k=1, num_shards=2, compute_chargrams=False)
    with pytest.raises(ValueError, match="positions"):
        merge_indexes([ia, iv1], str(tmp_path / "bad"), num_shards=2,
                      compute_chargrams=False)


def test_streaming_positions_equal_in_memory(tmp_path):
    """Streaming builds (single-device AND SPMD pass 2) with positions
    produce part AND positions files byte-identical to the in-memory
    positions build at the same shard count, and phrase queries work."""
    import filecmp

    from tpu_ir.index.streaming import build_index_streaming
    from tpu_ir.index.verify import verify_index
    from tpu_ir.search import Scorer

    p = tmp_path / "c.trec"
    p.write_text("".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in PHRASE_DOCS.items()))

    mem = str(tmp_path / "mem")
    build_index([str(p)], mem, k=1, num_shards=8, compute_chargrams=False,
                positions=True)

    stream = str(tmp_path / "stream")
    meta = build_index_streaming([str(p)], stream, k=1, num_shards=8,
                                 batch_docs=3, compute_chargrams=False,
                                 positions=True)
    assert meta.has_positions and meta.version == 2
    assert verify_index(stream)["ok"]

    spmd = str(tmp_path / "spmd")
    build_index_streaming([str(p)], spmd, k=1, batch_docs=3,
                          compute_chargrams=False, positions=True,
                          spmd_devices=8)
    assert verify_index(spmd)["ok"]

    for s in range(8):
        for name in (fmt.part_name(s), positions_name(s)):
            assert filecmp.cmp(os.path.join(mem, name),
                               os.path.join(stream, name),
                               shallow=False), ("stream", name)
            assert filecmp.cmp(os.path.join(mem, name),
                               os.path.join(spmd, name),
                               shallow=False), ("spmd", name)

    got = {d for d, _ in Scorer.load(stream).search('"salmon fishing"')}
    assert got == {"F-01", "F-04"}


def test_streaming_positions_resume(tmp_path, monkeypatch):
    """Crash-resume with positions: restart after a mid-pass-2 crash
    without re-tokenizing; positions files byte-identical to a clean
    streaming build."""
    import filecmp

    import tpu_ir.index.streaming as streaming
    from tpu_ir.index.streaming import build_index_streaming
    from tpu_ir.index.verify import verify_index

    p = tmp_path / "c.trec"
    p.write_text("".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in PHRASE_DOCS.items()))
    kw = dict(k=1, num_shards=3, batch_docs=2, compute_chargrams=False,
              positions=True)

    ref_dir = str(tmp_path / "ref")
    real_tok = streaming.make_chunked_tokenizer
    monkeypatch.setattr(
        streaming, "make_chunked_tokenizer",
        lambda paths, k=1, **kw: real_tok(paths, k=k, chunk_bytes=120,
                                          **kw))
    build_index_streaming([str(p)], ref_dir, **kw)

    out = str(tmp_path / "idx")
    real_post = streaming.build_postings_packed_jit
    calls = {"n": 0}

    def crashing(*a, **kws):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("injected")
        return real_post(*a, **kws)

    monkeypatch.setattr(streaming, "build_postings_packed_jit", crashing)
    with pytest.raises(RuntimeError, match="injected"):
        build_index_streaming([str(p)], out, **kw)
    monkeypatch.setattr(streaming, "build_postings_packed_jit", real_post)
    monkeypatch.setattr(
        streaming, "make_chunked_tokenizer",
        lambda *a, **kws: (_ for _ in ()).throw(
            AssertionError("resume must not re-tokenize")))
    build_index_streaming([str(p)], out, **kw)
    assert verify_index(out)["ok"]
    for s in range(3):
        for name in (fmt.part_name(s), positions_name(s)):
            assert filecmp.cmp(os.path.join(ref_dir, name),
                               os.path.join(out, name), shallow=False), name


def test_phrase_and_prox_layout_independent(phrase_index):
    """Phrase matching is host-side and the prox boost post-processes the
    rerank, so results must be identical across serving layouts —
    including the 8-virtual-device sharded mesh."""
    from tpu_ir.search import Scorer

    dense = Scorer.load(phrase_index, layout="dense")
    sparse = Scorer.load(phrase_index, layout="sparse")
    sharded = Scorer.load(phrase_index, layout="sharded")

    for q in ['"salmon fishing"', '"salmon fishing" fun']:
        want = dense.search(q)
        for s in (sparse, sharded):
            got = s.search(q)
            assert [(d, round(sc, 4)) for d, sc in got] == \
                   [(d, round(sc, 4)) for d, sc in want], (q, s.layout)

    want = dense.search("salmon fishing", rerank=6, prox=True)
    for s in (sparse, sharded):
        got = s.search("salmon fishing", rerank=6, prox=True)
        assert [d for d, _ in got] == [d for d, _ in want], s.layout
        for (_, a), (_, b) in zip(got, want):
            assert a == pytest.approx(b, rel=1e-5), s.layout


def test_show_matches_cli(phrase_index, capsys):
    """--show-matches prints each hit's query-term token positions from
    the v2 runs; a v1 index gets the documented error."""
    from tpu_ir.cli import main

    assert main(["search", phrase_index, "--backend", "cpu",
                 "-q", "salmon fishing", "--show-matches"]) == 0
    out = capsys.readouterr().out
    assert "salmon@" in out and "fish@" in out
    # F-01 analyzes to [01, salmon, fish, fun, salmon, tasti]
    # (DOCNO digits tokenize; stopwords vanish) => salmon@1,4 fish@2
    assert "salmon@1,4 fish@2" in out


def test_show_matches_requires_positions(tmp_path, capsys):
    from tpu_ir.cli import main
    from tpu_ir.index import build_index

    p = tmp_path / "c.trec"
    p.write_text("<DOC>\n<DOCNO> X </DOCNO>\n<TEXT>\nsalmon\n</TEXT>\n"
                 "</DOC>\n<DOC>\n<DOCNO> Y </DOCNO>\n<TEXT>\ntrout\n"
                 "</TEXT>\n</DOC>\n")
    out = str(tmp_path / "idx")
    build_index([str(p)], out, k=1, num_shards=2, compute_chargrams=False)
    assert main(["search", out, "--backend", "cpu", "-q", "salmon",
                 "--show-matches"]) == 1
    assert "position" in capsys.readouterr().err


def test_phrase_match_survives_zero_idf(tmp_path):
    """A phrase whose terms appear in EVERY doc (df == N -> TF-IDF idf 0)
    must still return its exact matches — the plain path's zero-score
    drop does not apply to an explicit phrase constraint ("to be or not
    to be" would otherwise return nothing). Found by the differential
    fuzz (seed 291)."""
    from tpu_ir.index import build_index
    from tpu_ir.search import Scorer

    docs = {
        "Z-1": "gold quick fish",        # adjacent "gold quick"
        "Z-2": "quick fish gold",        # both terms, not adjacent
        "Z-3": "fish gold market quick",  # both terms, not adjacent
        # (the separator must NOT be a stopword: positions index the
        # post-analysis stream, so "gold then quick" IS adjacent)
    }
    corpus = tmp_path / "c.trec"
    corpus.write_text("".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in docs.items()))
    idx = str(tmp_path / "idx")
    build_index([str(corpus)], idx, chargram_ks=[], num_shards=2,
                positions=True)
    s = Scorer.load(idx)
    got = s.search('"gold quick"')
    assert [d for d, _ in got] == ["Z-1"]
    assert got[0][1] == 0.0              # idf 0: matched at score zero
    # BM25's idf is always positive: same doc, positive score
    got_bm = s.search('"gold quick"', scoring="bm25")
    assert [d for d, _ in got_bm] == ["Z-1"] and got_bm[0][1] > 0
