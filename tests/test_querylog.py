"""Query-log + slow-query-trap acceptance (ISSUE 8).

Pins the four contracts of tpu_ir.obs.querylog:

- recording: every Scorer-answered query lands one entry with the
  attribution fields (hash/terms, level, stage split, batch id, top-k,
  prune decision); sampling and the ring bound hold; redaction strips
  readable terms but keeps the hash; the frontend's request_context
  stamps the ladder's true level;
- the slow-query trap: a forced slow query produces a capture with the
  request's span tree + a bit-exact explain + a `slow_query` flight
  record (readable via `tpu-ir querylog` and /querylog), the explain
  cost rides the flight recorder's rate gate, and flight-record
  headers carry the compact last-K slow entries;
- the scrape surfaces: /querylog, /doctor, /healthz's
  slow_queries_last_60s, and the cross-linked HTML nav;
- overhead: the always-on steady state costs <= 5% on the serve soak
  (same guard style as PR 3's <= 10% tracing pin).
"""

import json
import time
from pathlib import Path

import numpy as np
import pytest

import tpu_ir.faults as faults
from tpu_ir import obs
from tpu_ir.index import build_index
from tpu_ir.obs import querylog
from tpu_ir.search import Scorer
from tpu_ir.serving import ServingConfig, ServingFrontend
from tpu_ir.serving.soak import make_queries

WORDS = ("salmon fishing river bears honey quick brown fox lazy dog "
         "market investor asset bond stock season rain forest".split())


@pytest.fixture(autouse=True)
def _restore_querylog_config():
    yield
    querylog.configure(enabled=True, sample=1, ring_capacity=256,
                       redact=False, slow_ms=0.0, slow_keep=16)
    obs.configure(enabled=True)
    faults.clear()


@pytest.fixture(scope="module")
def index_dir(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("querylog")
    body = []
    for i in range(100):
        text = " ".join(WORDS[(i + j) % len(WORDS)]
                        for j in range(3 + i % 7))
        body.append(f"<DOC>\n<DOCNO> Q-{i:04d} </DOCNO>\n<TEXT>\n"
                    f"{text}\n</TEXT>\n</DOC>\n")
    corpus = tmp / "corpus.trec"
    corpus.write_text("".join(body))
    out = str(tmp / "idx")
    build_index([str(corpus)], out, num_shards=2,
                compute_chargrams=False)
    return out


@pytest.fixture(scope="module")
def scorer(index_dir):
    s = Scorer.load(index_dir, layout="sparse")
    s.search_batch(["salmon fishing"], k=5, scoring="bm25")
    s.search_batch(["salmon fishing"], k=5, scoring="tfidf")
    s.search_batch(["salmon fishing"], k=5, rerank=25)
    return s


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------


def test_entries_carry_attribution_fields(scorer):
    res = scorer.search_batch(["salmon fishing", "honey bears"], k=5,
                              scoring="bm25")
    entries = querylog.recent()
    assert len(entries) == 2
    a, b = entries
    assert a["batch_id"] == b["batch_id"] and a["batch_size"] == 2
    for e, text in zip(entries, ("salmon fishing", "honey bears")):
        assert e["level"] == "full" and e["degraded"] is False
        assert e["scoring"] == "bm25" and e["k"] == 5
        assert e["n_terms"] == 2 and len(e["query_hash"]) == 8
        assert e["total_ms"] >= e["dispatch_ms"] >= 0
        assert "analyze_ms" in e
        assert e["prune"]["dispatch_mode"] in ("all_skip", "all_full",
                                               "split")
        assert isinstance(e["prune"]["has_hot"], bool)
    # top-k docids + scores match the results
    assert entries[0]["top"][0][0] == res[0][0][0]
    assert entries[0]["top"][0][1] == pytest.approx(res[0][0][1],
                                                    abs=1e-6)
    assert entries[0]["terms"] == ["salmon", "fish"]


def test_sampling_keeps_every_nth(scorer):
    querylog.configure(sample=3)
    for i in range(9):
        scorer.search_batch([f"salmon query{i}"], k=2)
    assert len(querylog.recent()) == 3
    # the registry counter counts KEPT entries (the scrape contract)
    assert obs.get_registry().get("querylog.recorded") == 3


def test_ring_is_bounded(scorer):
    querylog.configure(ring_capacity=4)
    for i in range(10):
        scorer.search_batch(["honey"], k=2)
    assert len(querylog.recent()) == 4


def test_redaction_strips_terms_keeps_hash(scorer):
    querylog.configure(redact=True)
    scorer.search_batch(["salmon fishing"], k=3)
    e = querylog.recent()[-1]
    assert "terms" not in e
    assert len(e["query_hash"]) == 8
    querylog.configure(redact=False)
    scorer.search_batch(["salmon fishing"], k=3)
    e2 = querylog.recent()[-1]
    # the hash is the stable join key across the redaction switch
    assert e2["query_hash"] == e["query_hash"]
    assert e2["terms"] == ["salmon", "fish"]


def test_frontend_context_stamps_true_level(scorer):
    with querylog.request_context(level="no_rerank", queue_depth=3):
        scorer.search_batch(["honey bears"], k=3)
    e = querylog.recent()[-1]
    assert e["level"] == "no_rerank" and e["queue_depth"] == 3


def test_phrase_queries_record_slim_entries(index_dir, tmp_path):
    """Phrase queries run on the host pipeline; they still land in the
    log (positions-built index)."""
    corpus = tmp_path / "c.trec"
    corpus.write_text(
        "<DOC>\n<DOCNO> P-1 </DOCNO>\n<TEXT>\nsalmon river fishing\n"
        "</TEXT>\n</DOC>\n"
        "<DOC>\n<DOCNO> P-2 </DOCNO>\n<TEXT>\nriver salmon\n</TEXT>\n"
        "</DOC>\n")
    idx = str(tmp_path / "pidx")
    build_index([str(corpus)], idx, compute_chargrams=False,
                positions=True)
    s = Scorer.load(idx)
    res = s.search_batch(['"salmon river"'], k=5)
    assert res[0]
    e = querylog.recent()[-1]
    assert e.get("phrase") is True and e["total_ms"] >= 0
    assert e["top"][0][0] == res[0][0][0]


def test_disabled_querylog_records_nothing(scorer):
    querylog.configure(enabled=False)
    scorer.search_batch(["salmon"], k=2)
    assert querylog.recent() == []
    assert querylog.summary()["enabled"] is False


# ---------------------------------------------------------------------------
# the slow-query trap
# ---------------------------------------------------------------------------


def test_slow_query_trap_end_to_end(scorer, tmp_path, monkeypatch):
    """THE acceptance pin: a forced slow query produces a flight record
    containing its explain + span tree, reachable via `tpu-ir querylog`
    and /querylog."""
    monkeypatch.setenv("TPU_IR_FLIGHT_DIR", str(tmp_path))
    querylog.configure(slow_ms=0.0001)   # everything is slow
    obs.reset_rate_limit()
    frontend = ServingFrontend(scorer)
    res = frontend.search("salmon fishing", k=5, scoring="bm25")
    assert res.level == "full"
    caps = querylog.slow_recent()
    assert caps, "no slow capture"
    cap = caps[-1]
    assert cap["slow"] is True
    # span tree: the frontend's still-open request root
    assert cap["span_tree"]["name"] == "request"
    assert any(c["name"] == "dispatch"
               for c in cap["span_tree"]["children"])
    # explain: bit-exact decomposition of the top hit
    ex = cap["explain"][0]
    assert ex["contribution_sum"] == ex["score"] == res[0][1]
    # flight record on disk, explain + slow window in the header
    path = cap["flight_record"]
    assert path and Path(path).exists()
    recs = [json.loads(line) for line in open(path)]
    header = recs[0]
    assert header["reason"] == "slow_query"
    assert header["extra"]["slow_query"]["explain"][0]["score"] == \
        ex["score"]
    assert header["slow_queries"] and \
        header["slow_queries"][-1]["query_hash"] == cap["query_hash"]
    assert recs[-1]["record"] == "telemetry"
    # the registry counters + the health window see it
    assert obs.get_registry().get("querylog.slow") >= 1
    assert querylog.slow_last_60s() >= 1

    # ... and the CLI surfaces the capture
    from tpu_ir.cli import main
    import io, contextlib

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert main(["querylog", "--slow"]) == 0
    out = json.loads(buf.getvalue())
    assert out["slow_entries"][-1]["query_hash"] == cap["query_hash"]
    assert out["slow_entries"][-1]["explain"][0]["score"] == ex["score"]


def test_slow_trap_explain_rides_the_rate_gate(scorer, tmp_path,
                                               monkeypatch):
    """A storm of slow queries must not multiply load with explain
    dispatches: only a dump the per-reason rate limit admits computes
    one."""
    monkeypatch.setenv("TPU_IR_FLIGHT_DIR", str(tmp_path))
    querylog.configure(slow_ms=0.0001)
    obs.reset_rate_limit()
    scorer.search_batch(["salmon fishing"], k=3, scoring="bm25")
    scorer.search_batch(["honey bears"], k=3, scoring="bm25")
    caps = querylog.slow_recent()
    assert len(caps) == 2
    assert caps[0].get("explain") and caps[0]["flight_record"]
    # second offender inside the interval: captured, but no explain
    # dispatches and no second artifact
    assert caps[1].get("explain") is None
    assert caps[1]["flight_record"] is None
    assert len(list(Path(tmp_path).glob("*slow_query.jsonl"))) == 1


def test_slow_capture_without_frontend_uses_ring_span(scorer, tmp_path,
                                                      monkeypatch):
    monkeypatch.setenv("TPU_IR_FLIGHT_DIR", str(tmp_path))
    querylog.configure(slow_ms=0.0001)
    obs.reset_rate_limit()
    scorer.search_batch(["salmon fishing"], k=3, scoring="bm25")
    cap = querylog.slow_recent()[-1]
    assert cap.get("span_tree") is not None
    assert cap.get("span_tree_source") == "ring"


# ---------------------------------------------------------------------------
# scrape surfaces: /querylog, /doctor, /healthz, nav
# ---------------------------------------------------------------------------


def _get(url: str) -> bytes:
    import urllib.request

    return urllib.request.urlopen(url, timeout=10).read()


def test_server_querylog_doctor_healthz_and_nav(scorer, index_dir):
    from tpu_ir.obs.server import start_server

    scorer.search_batch(["salmon fishing"], k=3)
    srv = start_server(port=0)
    try:
        ql = json.loads(_get(f"{srv.url}/querylog"))
        assert ql["ring"]["capacity"] >= 1
        assert ql["entries"][-1]["query_hash"]
        ql_slow = json.loads(_get(f"{srv.url}/querylog?slow=1"))
        assert "entries" not in ql_slow and "slow_entries" in ql_slow

        h = json.loads(_get(f"{srv.url}/healthz"))
        assert h["slow_queries_last_60s"] is not None

        dr = json.loads(_get(f"{srv.url}/doctor"))
        assert index_dir in list(dr["indexes"]) or dr["indexes"]
        rep = list(dr["indexes"].values())[0]
        assert "tiers" in rep and "shards" in rep
        # a second scrape serves the cached report (same object shape)
        dr2 = json.loads(_get(f"{srv.url}/doctor"))
        assert dr2 == dr
        # unregistered paths are refused, not read
        bad = json.loads(_get(f"{srv.url}/doctor?index=/etc"))
        assert "error" in bad

        # nav cross-links on every HTML page
        for page in ("/jobs?format=html", "/querylog?format=html",
                     "/doctor?format=html", "/profile?format=html"):
            html = _get(f"{srv.url}{page}").decode()
            for target in ("/querylog?format=html", "/doctor?format=html",
                           "/jobs?format=html", "/profile?format=html",
                           "/healthz"):
                assert target in html, (page, target)
    finally:
        srv.stop()


def test_querylog_counters_are_declared(scorer):
    """Lint TPU303 contract: the querylog names are declared, so the
    registry pre-registers them and the scrape surfaces always show
    them (the coverage-by-construction idiom)."""
    names = set(obs.get_registry().counter_names())
    assert {"querylog.recorded", "querylog.slow"} <= names
    assert "querylog.slow_capture" in obs.DECLARED_HISTOGRAMS
    assert "explain" in obs.DECLARED_HISTOGRAMS


def test_serve_bench_report_carries_querylog(index_dir, capsys):
    from tpu_ir.cli import main

    rc = main(["serve-bench", index_dir, "--threads", "2", "--queries",
               "12", "--deadline", "5.0"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["querylog"]["recorded"] >= 12
    assert "slow_entries" in out["querylog"]


# ---------------------------------------------------------------------------
# overhead
# ---------------------------------------------------------------------------


def test_querylog_overhead_within_bound(scorer):
    """The steady-state pin: a 200-query serving soak with the query
    log on stays close to off — same guard style as the PR 3 tracing
    pin. Thresholds are sized for PARALLEL CI, not an idle box (the
    ISSUE 12 deflake): best-of-N absorbs one descheduled run, the 10%
    relative term still catches a real per-entry regression (the log's
    actual cost measured ~1%), and the absolute slack covers the
    scheduler/GC spikes a loaded 2-core container lands on EITHER arm
    of the comparison. Under heavy external load the comparison is
    meaningless noise — detected via a control re-run of the SAME arm
    and skipped rather than flaking."""
    reqs = make_queries(scorer, 200, seed=7)
    frontend = ServingFrontend(scorer, ServingConfig(
        max_concurrency=4, max_queue=16))

    def soak_once() -> float:
        t0 = time.perf_counter()
        for r in reqs:
            frontend.search(r["text"], k=r["k"], scoring=r["scoring"],
                            rerank=r["rerank"])
        return time.perf_counter() - t0

    soak_once()                      # warm every query shape
    timings = {}
    spread = {}
    for enabled in (True, False):
        querylog.configure(enabled=enabled)
        runs = sorted(soak_once() for _ in range(3))
        timings[enabled] = runs[0]
        spread[enabled] = runs[-1] / max(runs[0], 1e-9)
    querylog.configure(enabled=True)
    if max(spread.values()) > 1.35:
        # same-arm repeats disagreeing by >35% means the box is under
        # external load — the A/B delta is weather, not signal. The
        # gate is deliberately TIGHTER than the assertion margin
        # (ISSUE 16 deflake): a run noisy enough to need the wide
        # margin is a run this gate should already have skipped.
        pytest.skip(f"host too loaded for a timing comparison "
                    f"(same-arm spread {spread})")
    assert timings[True] <= timings[False] * 1.15 + 1.0, (
        f"querylog overhead too high: on {timings[True]:.3f}s vs "
        f"off {timings[False]:.3f}s")
