"""Differential build fuzz, suite-sized slice: 2 seeds of the
experiments/fuzz_builds.py harness (random corpus -> four build paths
byte-identical + merge determinism + compat-oracle agreement). The full
sweep (100 seeds) ran clean in r5 — NOTES.md records it; this keeps the
harness continuously exercised."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "experiments"))

import pytest


@pytest.mark.parametrize(
    "seed", [pytest.param(201, marks=pytest.mark.slow), 202])
def test_fuzz_seed(seed):
    from fuzz_builds import one_seed

    one_seed(seed)
