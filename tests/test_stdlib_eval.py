"""Real-corpus quality gate (VERDICT r4 next #3): the in-repo frozen
CPython-docs collection (data/stdlib/ — third-party text, hand-judged
graded qrels) must retrieve well through the FULL standard loop
(index -> topics -> --trec-run -> evaluate_run). Unlike every other
quality test, neither the corpus nor the judgments came from this
framework — a collapsed analyzer, broken idf, or scoring regression
cannot stay above these floors by construction."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import bench  # noqa: E402 — repo root on sys.path first


def test_stdlib_real_corpus_quality(tmp_path, capsys):
    out = bench.run_stdlib_eval(str(tmp_path))
    assert out["real_eval"] == "ok", out
    # the bench's stdout contract is ONE JSON line; the embedded eval
    # loop must not leak the CLI's metadata/result printing (a stray
    # metadata line broke the msmarco artifact in r5)
    assert capsys.readouterr().out == ""
    assert out["real_queries"] == 80
    # floors well below the freeze-time measurements (MRR 0.93 /
    # NDCG@10 0.79) but unreachable for a degenerate ranker: with 144
    # docs and k=10, random ranking gives MRR ~0.02
    assert out["real_bm25_mrr"] >= bench._REAL_MRR_FLOOR
    assert out["real_bm25_ndcg_at_10"] >= bench._REAL_NDCG_FLOOR
    assert out["real_rerank_mrr"] >= bench._REAL_MRR_FLOOR
    assert out["real_rerank_ndcg_at_10"] >= bench._REAL_NDCG_FLOOR


def test_stdlib_collection_integrity():
    """Every qrels judgment refers to a doc in the corpus; every topic
    has at least one grade-2 judgment."""
    import re

    data = os.path.join(os.path.dirname(os.path.abspath(bench.__file__)),
                        "data", "stdlib")
    docs = set(re.findall(r"<DOCNO> (\S+) </DOCNO>",
                          open(os.path.join(data, "corpus.trec")).read()))
    assert len(docs) == 144
    best: dict[str, int] = {}
    for line in open(os.path.join(data, "qrels.txt")):
        qid, _, docid, grade = line.split()
        assert docid in docs, docid
        best[qid] = max(best.get(qid, 0), int(grade))
    topics = len(re.findall(r"<num>", open(
        os.path.join(data, "topics.trec")).read()))
    assert topics == 80 and len(best) == 80
    assert all(g == 2 for g in best.values())
