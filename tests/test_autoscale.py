"""Elastic ShardSet acceptance suite (ISSUE 16).

The robustness contract, unit-level and end-to-end:

- **membership protocol**: grow() publishes WARM replicas (precompile +
  residency done before the dispatch grid sees them — compile.count
  must not move once traffic flows), begin_drain removes a replica
  from dispatchable() (and therefore from breaker probes and the hedge
  p99) while addresses() keeps it visible, retirement is drain-not-drop;
- **control loop**: hysteresis (sustain_up/sustain_down consecutive
  ticks), cooldown (suppressed decisions counted), min/max clamps,
  highest-index-active drain pick — all deterministic via tick(now=);
- **conservation across membership changes**: the routed soak with a
  scripted scale plan (grow mid-run, drain mid-run, SIGKILL during the
  drain handshake) still satisfies shed + served == submitted with
  zero errors;
- **zero-stale swap-during-scale**: a rolling generation swap
  concurrent with a scale-up never lets a stale-generation response
  out after the roll confirms (late_old_generation == 0).
"""

import json
import random
import threading
import time

import pytest

from tpu_ir.index.ingest import IngestWriter
from tpu_ir.index.segments import LiveIndex
from tpu_ir.index.streaming import build_index_streaming
from tpu_ir.obs import get_registry
from tpu_ir.obs.registry import (
    DECLARED_COUNTERS,
    DECLARED_HISTOGRAMS,
    SCALE_COUNTER_NAMES,
)
from tpu_ir.serving import (
    Autoscaler,
    AutoscaleConfig,
    Router,
    RouterConfig,
    ShardSet,
    autoscale_enabled,
    run_distributed_soak,
)
from tpu_ir.serving.shardset import get_worker_health

WORDS = ("salmon fishing river bears honey quick brown fox lazy dog "
         "market investor asset bond stock season rain forest".split())

QUERIES = ["salmon fishing", "bears honey market", "quick",
           "rain forest investor", "asset bond stock season",
           "dog dog salmon", "fox market rain"]


def _write_corpus(path, n_docs=120):
    body = []
    for i in range(n_docs):
        text = " ".join(WORDS[(i + j) % len(WORDS)]
                        for j in range(3 + (i % 7)))
        body.append(f"<DOC>\n<DOCNO> D-{i:04d} </DOCNO>\n<TEXT>\n"
                    f"{text}\n</TEXT>\n</DOC>\n")
    path.write_text("".join(body))
    return str(path)


@pytest.fixture(scope="module")
def index_dir(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("autoscale")
    corpus = _write_corpus(tmp / "corpus.trec")
    out = str(tmp / "idx")
    build_index_streaming([corpus], out, k=1, num_shards=2,
                          batch_docs=40, chargram_ks=[])
    return out


# ---------------------------------------------------------------------------
# deterministic control-loop units (fake fleet, explicit clock)
# ---------------------------------------------------------------------------


class FakeFleet:
    """A lifecycle-faithful in-memory stand-in for ShardSet."""

    def __init__(self, shards=2, replicas=1):
        self._life = [["active"] * replicas for _ in range(shards)]
        self._epoch = 0
        self._events = []
        self.retired = []
        self.grow_raises = False
        self.max_concurrency = 4

    def lifecycle(self):
        return [list(row) for row in self._life]

    def epoch(self):
        return self._epoch

    def events(self):
        return list(self._events)

    def active_replicas(self, shard=None):
        counts = [sum(1 for st in row if st == "active")
                  for row in self._life]
        return counts[shard] if shard is not None else min(counts)

    def grow(self):
        if self.grow_raises:
            raise RuntimeError("spawn failed")
        added = []
        for s, row in enumerate(self._life):
            row.append("active")
            self._epoch += 1
            self._events.append(("up", s, len(row) - 1, self._epoch))
            added.append((s, len(row) - 1))
        return added

    def retire_replica(self, shard, replica, *, drain_timeout_s=30.0):
        self._life[shard][replica] = "retired"
        self._epoch += 1
        self._events.append(("down", shard, replica, self._epoch))
        self.retired.append((shard, replica))
        return {"shard": shard, "replica": replica, "drain_s": 0.0,
                "inflight_peak": 0, "drained_clean": True,
                "killed_mid_drain": False}


class FakeAdmission:
    def __init__(self):
        self.inflight = 0
        self.queued = 0
        self.max_concurrency = 10

    def in_flight(self):
        return self.inflight

    def queue_depth(self):
        return self.queued


class FakeRouter:
    def __init__(self):
        self.admission = FakeAdmission()
        self.resets = []

    def reset_breaker(self, shard, replica):
        self.resets.append((shard, replica))


def _cfg(**kw):
    base = dict(min_replicas=1, max_replicas=3, cooldown_s=1.0,
                up_occupancy=0.8, down_occupancy=0.2,
                sustain_up=3, sustain_down=5)
    base.update(kw)
    return AutoscaleConfig(**base)


def test_hysteresis_scales_up_only_after_sustained_pressure():
    fleet, router = FakeFleet(), FakeRouter()
    a = Autoscaler(fleet, router, _cfg())
    router.admission.inflight = 9          # occupancy 0.9 >= 0.8
    assert a.tick(now=1.0)["action"] is None
    assert a.tick(now=2.0)["action"] is None
    assert fleet.active_replicas() == 1
    d = a.tick(now=3.0)                    # third consecutive tick
    assert d["action"] == "up" and d["reason"] == "sustained_pressure"
    assert fleet.active_replicas() == 2
    # a reused slot must not inherit breaker history
    assert router.resets == d["slots"] == [(0, 1), (1, 1)]
    # one blip does NOT re-arm: counters reset after the action
    assert a.tick(now=3.1)["action"] is None


def test_cooldown_suppresses_and_counts_then_releases():
    fleet, router = FakeFleet(), FakeRouter()
    a = Autoscaler(fleet, router, _cfg(cooldown_s=5.0))
    router.admission.inflight = 9
    for now in (1.0, 2.0, 3.0):
        a.tick(now=now)                    # scales up at now=3
    assert fleet.active_replicas() == 2
    skipped0 = get_registry().get("scale.cooldown_skipped")
    for now in (3.2, 3.4, 3.6):
        d = a.tick(now=now)                # re-armed but inside cooldown
    assert d["action"] is None and d["reason"] == "cooldown"
    assert get_registry().get("scale.cooldown_skipped") > skipped0
    assert fleet.active_replicas() == 2
    d = a.tick(now=9.0)                    # cooldown (until 8.0) expired
    assert d["action"] == "up"
    assert fleet.active_replicas() == 3


def test_clamps_at_max_and_min_replicas():
    fleet, router = FakeFleet(replicas=3), FakeRouter()
    a = Autoscaler(fleet, router, _cfg(max_replicas=3, sustain_down=3))
    router.admission.inflight = 9
    for now in (1.0, 2.0, 3.0):
        d = a.tick(now=now)
    assert d["action"] is None and d["reason"] == "at_max_replicas"

    lone = FakeFleet(replicas=1)
    b = Autoscaler(lone, router, _cfg(sustain_down=3))
    router.admission.inflight = 0          # occupancy 0 <= 0.2
    for now in (11.0, 12.0, 13.0):
        d = b.tick(now=now)
    assert d["action"] is None and d["reason"] == "at_min_replicas"
    assert lone.retired == []


def test_scale_down_drains_highest_active_replica_per_shard():
    fleet, router = FakeFleet(replicas=3), FakeRouter()
    # shard 1's top slot is already retired: its pick must skip it
    fleet._life[1][2] = "retired"
    a = Autoscaler(fleet, router, _cfg(sustain_down=3, cooldown_s=0.1))
    router.admission.inflight = 0
    for now in (1.0, 2.0, 3.0):
        d = a.tick(now=now)
    assert d["action"] == "down" and d["reason"] == "sustained_idleness"
    assert fleet.retired == [(0, 2), (1, 1)]


def test_failed_grow_does_not_kill_the_loop():
    fleet, router = FakeFleet(), FakeRouter()
    fleet.grow_raises = True
    a = Autoscaler(fleet, router, _cfg())
    router.admission.inflight = 9
    for now in (1.0, 2.0, 3.0):
        d = a.tick(now=now)
    assert d["action"] is None and d["reason"].startswith("up_failed")
    # the counters stayed armed (no action executed, no cooldown), so
    # the very next tick retries — and succeeds once spawning works
    fleet.grow_raises = False
    d = a.tick(now=4.0)
    assert d["action"] == "up"
    assert fleet.active_replicas() == 2


def test_env_resolution_and_validation(monkeypatch):
    monkeypatch.setenv("TPU_IR_SCALE_MIN_REPLICAS", "2")
    monkeypatch.setenv("TPU_IR_SCALE_MAX_REPLICAS", "7")
    monkeypatch.setenv("TPU_IR_SCALE_COOLDOWN_S", "2.5")
    cfg = AutoscaleConfig().resolved()
    assert (cfg.min_replicas, cfg.max_replicas, cfg.cooldown_s) \
        == (2, 7, 2.5)
    assert not autoscale_enabled()
    monkeypatch.setenv("TPU_IR_AUTOSCALE", "1")
    assert autoscale_enabled()
    assert not autoscale_enabled(flag=False)  # explicit flag wins
    monkeypatch.setenv("TPU_IR_SCALE_MAX_REPLICAS", "1")
    with pytest.raises(ValueError):
        Autoscaler(FakeFleet(), FakeRouter(), AutoscaleConfig())


def test_scale_telemetry_names_are_declared():
    """Satellite 3: the scale counters/histograms ship DECLARED — the
    lint contract (TPU303/305/306) keys off these tuples."""
    assert set(SCALE_COUNTER_NAMES) == {
        "scale.up", "scale.down", "scale.drain_inflight",
        "scale.cooldown_skipped"}
    assert set(SCALE_COUNTER_NAMES) <= set(DECLARED_COUNTERS)
    assert {"scale.drain_ms", "scale.warmup_ms"} \
        <= set(DECLARED_HISTOGRAMS)


def test_healthz_carries_autoscaler_section():
    """Satellite 5: /healthz shows epoch, per-replica lifecycle, and
    the last decision + reason of the newest live autoscaler."""
    from tpu_ir.obs.server import health_snapshot

    fleet, router = FakeFleet(), FakeRouter()
    a = Autoscaler(fleet, router, _cfg())
    router.admission.inflight = 9
    for now in (1.0, 2.0, 3.0):
        a.tick(now=now)
    snap = health_snapshot()
    az = snap.get("autoscaler")
    assert az is not None
    assert az["enabled"] is True
    assert az["epoch"] == fleet.epoch() > 0
    assert az["lifecycle"] == fleet.lifecycle()
    assert az["last_decision"]["action"] == "up"
    assert az["config"]["max_replicas"] == 3
    assert a is not None  # keep the weakref target alive to here


# ---------------------------------------------------------------------------
# the real fleet: membership protocol + warm-start + drain-not-drop
# ---------------------------------------------------------------------------


def test_grow_is_warm_and_drain_never_drops(index_dir, tmp_path):
    """One elastic lifecycle against real subprocess workers:

    - grow() publishes one warm replica per shard (epoch bumped, "up"
      events logged, dispatchable == addresses);
    - WARM means warm: the new replicas' own compile counters do not
      move once routed traffic flows through them, and no breaker
      opens (no compile-storm 5xx/timeouts on first contact);
    - begin_drain removes the replica from dispatchable() (so breaker
      probes and hedge sampling can't reach it) while addresses()
      still shows it;
    - retiring under concurrent traffic drains clean — every in-flight
      request is served or shed, never errored;
    - a retired slot is REUSED by the next grow (bounded grid width).
    """
    reg = get_registry()
    with ShardSet(index_dir, shards=2, replicas=1, layout="sparse",
                  deadline_s=3.0, rundir=str(tmp_path / "run")) as ss:
        router = Router(index_dir, ss,
                        RouterConfig(deadline_ms=8000.0, max_queue=64))
        try:
            assert ss.active_replicas() == 1
            e0 = ss.epoch()
            opened0 = reg.get("router.breaker_opened")
            up0 = reg.get("scale.up")

            added = ss.grow()
            assert added == [(0, 1), (1, 1)]
            assert ss.active_replicas() == 2
            assert ss.epoch() > e0
            assert reg.get("scale.up") - up0 == 2
            assert [ev[0] for ev in ss.events()] == ["up", "up"]
            assert ss.dispatchable() == ss.addresses()
            assert ss.lifecycle() == [["active", "active"]] * 2

            new_addrs = [ss.addresses()[s][r] for s, r in added]
            compiles0 = {}
            for addr in new_addrs:
                w = get_worker_health(addr, 10.0)["worker"]
                compiles0[addr] = w["compiles"]["count"]
                assert w["in_flight"] == 0

            for q in QUERIES * 3:
                res = router.search(q, k=10, scoring="bm25")
                assert Router.classify(res) == "full"

            # warm-start contract: entering the grid compiled NOTHING
            # new — the precompile walk ran before the ready file
            for addr in new_addrs:
                w = get_worker_health(addr, 10.0)["worker"]
                assert w["compiles"]["count"] == compiles0[addr], \
                    f"scale-up cold-compiled on {addr}"
            assert reg.get("router.breaker_opened") == opened0

            # drain visibility: out of dispatch, still addressable
            ss.begin_drain(0, 1)
            assert ss.lifecycle()[0][1] == "draining"
            assert router._replica_draining(0, 1)
            assert ss.dispatchable()[0][1] is None
            assert ss.addresses()[0][1] is not None

            # retire both grown replicas under live traffic
            results = []
            stop = threading.Event()

            def client():
                i = 0
                while not stop.is_set():
                    try:
                        router.search(QUERIES[i % len(QUERIES)], k=10,
                                      scoring="bm25")
                        results.append("ok")
                    except Exception as e:  # noqa: BLE001
                        results.append(repr(e))
                    i += 1

            threads = [threading.Thread(target=client, daemon=True)
                       for _ in range(4)]
            for t in threads:
                t.start()
            time.sleep(0.2)
            down0 = reg.get("scale.down")
            drains = [ss.retire_replica(s, 1, drain_timeout_s=20.0)
                      for s in range(2)]
            time.sleep(0.2)
            stop.set()
            for t in threads:
                t.join(timeout=30.0)
            assert all(d["drained_clean"] for d in drains), drains
            assert not any(d["killed_mid_drain"] for d in drains)
            assert reg.get("scale.down") - down0 == 2
            assert ss.active_replicas() == 1
            assert ss.dispatchable()[0][1] is None
            assert ss.dispatchable()[1][1] is None
            bad = [r for r in results if r != "ok"]
            assert not bad, bad[:5]
            assert results.count("ok") > 0

            # slot reuse: the next grow lands back in slot 1
            assert ss.grow() == [(0, 1), (1, 1)]
            assert ss.active_replicas() == 2
            for q in QUERIES:
                assert Router.classify(
                    router.search(q, k=10, scoring="bm25")) == "full"
        finally:
            router.close()


def test_conservation_across_membership_changes(index_dir, tmp_path):
    """THE robustness acceptance: grow mid-soak, drain mid-soak, and
    SIGKILL one replica WHILE its drain handshake is polling — and the
    PR-10 ledger still balances: shed + served == submitted, zero
    errors, zero deadlocks, zero result mismatches."""
    report = run_distributed_soak(
        str(index_dir), shards=2, replicas=2, threads=6, queries=90,
        seed=2, chaos=False,
        scale_plan={"up_at": 0.2, "down_at": 0.5,
                    "kill_during_drain": True},
        worker_deadline_s=3.0,
        router_config=RouterConfig(deadline_ms=8000.0, max_queue=128),
        rundir=str(tmp_path / "run"),
        flight_dir=str(tmp_path / "flight"),
        recovery_timeout_s=120.0)
    assert report["served"] + report["shed"] == report["submitted"]
    assert report["errors"] == 0, report["error_samples"]
    assert report["deadlocked"] == 0
    assert report["full_mismatches"] == 0
    assert report["partial_mismatches"] == 0
    sc = report["scale"]
    assert sc["events"] >= 4               # 2 up + 2 down, at least
    assert len(sc["drains"]) == 2
    # the scripted kill raced at least one drain handshake
    assert sc["killed_mid_drain"] + sc["drained_clean"] == 2
    assert sc["epoch"] > 0
    assert sc["mean_replicas"] > 0
    assert 0.0 <= sc["overprovision_fraction"] <= 1.0
    assert report["recovery_full"] == report["recovery_probes"]


@pytest.mark.slow
def test_zero_stale_swap_during_scale(tmp_path):
    """Rolling generation swap CONCURRENT with a scale-up: the walker's
    epoch-stability loop must also confirm the replica that grew into
    the grid mid-roll — no stale-generation response after the roll
    confirms, no unknown generation, conservation intact."""
    live = str(tmp_path / "live")
    LiveIndex.create(live, num_shards=2)
    rng = random.Random(5)
    with IngestWriter(live, auto_merge=False) as w:
        for i in range(50):
            w.add(f"D-{i:03d}",
                  " ".join(rng.choice(WORDS)
                           for _ in range(rng.randint(3, 7))))
        w.compact_all(note="base")
    report = run_distributed_soak(
        live, shards=2, replicas=1, threads=6, queries=80, seed=3,
        chaos=False, upgrade_at=0.25, upgrade_docs=6,
        scale_plan={"up_at": 0.3},
        worker_deadline_s=3.0,
        router_config=RouterConfig(deadline_ms=8000.0, max_queue=128),
        rundir=str(tmp_path / "run"),
        flight_dir=str(tmp_path / "flight"),
        recovery_timeout_s=120.0)
    assert report["served"] + report["shed"] == report["submitted"]
    assert report["errors"] == 0, report["error_samples"]
    assert report["deadlocked"] == 0
    up = report["upgrade"]
    assert up["swap"] is not None and not up["swap"]["failed"]
    assert up["late_old_generation"] == 0
    assert report["unknown_generation"] == 0
    assert report["full_mismatches"] == 0
    assert report["partial_mismatches"] == 0
    assert report["generations_served"].get(
        str(up["generation_b"]), 0) > 0
    assert report["scale"]["events"] >= 2  # the mid-roll grow landed
    assert report["recovery_full"] == report["recovery_probes"]


@pytest.mark.slow
def test_autoscaler_closed_loop_scales_up_under_burst(index_dir,
                                                      tmp_path):
    """The closed loop end to end: a burst workload through a
    deliberately narrow router (max_concurrency=2) sustains occupancy
    over the up threshold; the autoscaler grows the fleet mid-soak and
    the run still conserves."""
    report = run_distributed_soak(
        str(index_dir), shards=2, replicas=1, threads=8, queries=90,
        seed=4, chaos=False, autoscale=True,
        workload={"kind": "zipf", "skew": 0.8, "burst": 3.0},
        worker_deadline_s=3.0,
        router_config=RouterConfig(deadline_ms=8000.0,
                                   max_concurrency=2, max_queue=128),
        rundir=str(tmp_path / "run"),
        flight_dir=str(tmp_path / "flight"),
        recovery_timeout_s=120.0)
    assert report["served"] + report["shed"] == report["submitted"]
    assert report["errors"] == 0, report["error_samples"]
    assert report["deadlocked"] == 0
    sc = report["scale"]
    assert sc["autoscaler"]["enabled"] is True
    assert sc["events"] >= 2               # grew one replica per shard
    assert sc["mean_replicas"] >= 1.0
    assert report["burst_p99_ms"] > 0
    assert report["recovery_full"] == report["recovery_probes"]


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------


def test_cli_autoscale_requires_shards(index_dir, capsys):
    from tpu_ir.cli import main

    assert main(["serve-bench", index_dir, "--autoscale"]) == 2


@pytest.mark.slow
def test_cli_serve_bench_autoscale_smoke(index_dir, tmp_path, capsys,
                                         monkeypatch):
    """`tpu-ir serve-bench --autoscale`: elastic arm + static control
    arm, one history row carrying the ISSUE 16 trio of metrics."""
    from tpu_ir.obs import bench_check
    from tpu_ir.cli import main

    # keep the smoke row out of the checked-in repo trajectory
    hist = tmp_path / "BENCH_HISTORY.jsonl"
    monkeypatch.setattr(bench_check, "default_history_path",
                        lambda: str(hist))

    rc = main(["serve-bench", index_dir, "--shards", "2",
               "--replicas", "1", "--threads", "4", "--queries", "24",
               "--autoscale", "--deadline", "3.0", "--seed", "7"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    report = json.loads(out)
    assert rc == 0, report.get("history_row")
    row = report["history_row"]
    assert "-autoscale" in row["config"]
    for key in ("scale_events", "burst_p99_ms",
                "overprovision_fraction", "mean_replicas",
                "static_replicas", "static_burst_p99_ms",
                "forecast_burst_p99_ms", "forecast_lead_s",
                "reactive_lead_s"):
        assert key in row, key
    assert report["static_control"]["replicas"] >= 1
    assert report["served"] + report["shed"] == report["submitted"]
    # the predictive A/B arm (ISSUE 19) conserves like the others
    fc = report["forecast_arm"]
    assert fc["errors"] == 0
    assert fc["served"] + fc["shed"] == report["submitted"]
    lines = hist.read_text().splitlines()
    assert len(lines) == 1
