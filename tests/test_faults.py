"""Fault-injection acceptance suite (the tentpole contract): every injected
fault class — failed spill write, corrupt artifact, mid-pass process death,
all_to_all capacity overflow, slow/hung or device-lost score dispatch — must
end in either FULL RECOVERY with byte-identical artifacts or ONE structured
error. Never a hang, a traceback-to-user, or a silently wrong index.

Faults are driven through tpu_ir.faults' deterministic plan (the same
machinery TPU_IR_FAULTS / --faults exposes), so what these tests prove is
exactly what an operator can replay."""

import filecmp
import os
import time

import numpy as np
import pytest

import tpu_ir.faults as faults
import tpu_ir.index.streaming as streaming
from tpu_ir.index import format as fmt
from tpu_ir.index.streaming import PASS1_MANIFEST, build_index_streaming
from tpu_ir.index.verify import verify_index
from tpu_ir.search import Scorer
from tpu_ir.utils.report import recovery_counters

WORDS = ("salmon fishing river bears honey quick brown fox lazy dog "
         "market investor asset bond stock season rain forest".split())

BUILD_KW = dict(k=1, num_shards=3, batch_docs=25, chargram_ks=[2])


@pytest.fixture(autouse=True)
def _clean_fault_state():
    faults.clear()
    recovery_counters().reset()
    yield
    faults.clear()
    recovery_counters().reset()


def write_corpus(path, n_docs=120):
    body = []
    for i in range(n_docs):
        text = " ".join(WORDS[(i + j) % len(WORDS)]
                        for j in range(3 + (i % 7)))
        body.append(f"<DOC>\n<DOCNO> D-{i:04d} </DOCNO>\n<TEXT>\n"
                    f"{text}\n</TEXT>\n</DOC>\n")
    path.write_text("".join(body))
    return str(path)


def artifact_names(d):
    return sorted(
        n for n in os.listdir(d)
        if not n.startswith(".") and n != fmt.JOBS_DIR
        and not n.startswith("serving-"))


def assert_identical(got_dir, want_dir):
    names = artifact_names(want_dir)
    assert artifact_names(got_dir) == names
    for n in names:
        assert filecmp.cmp(os.path.join(want_dir, n),
                           os.path.join(got_dir, n), shallow=False), n


_REAL_TOKENIZER = streaming.make_chunked_tokenizer


def small_chunks(monkeypatch):
    """Tiny read chunks so the corpus spans several spill batches."""
    monkeypatch.setattr(
        streaming, "make_chunked_tokenizer",
        lambda paths, k=1, **kw: _REAL_TOKENIZER(paths, k=k,
                                                 chunk_bytes=400, **kw))


def forbid_tokenizer(monkeypatch):
    def boom(*a, **kw):
        raise AssertionError("resume must not re-tokenize the corpus")
    monkeypatch.setattr(streaming, "make_chunked_tokenizer", boom)


@pytest.fixture(scope="module")
def ref(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("faults_ref")
    corpus = write_corpus(tmp / "corpus.trec")
    ref_dir = str(tmp / "ref")
    build_index_streaming([corpus], ref_dir, **BUILD_KW)
    return corpus, ref_dir


def _flip_byte(path, offset=None):
    """In-place single-byte corruption (size-preserving bit rot)."""
    size = os.path.getsize(path)
    offset = size // 2 if offset is None else offset
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


# ---------------------------------------------------------------------------
# fault plan mechanics
# ---------------------------------------------------------------------------


def test_plan_parsing_and_determinism():
    plan = faults.parse_plan(
        "spill_write@pairs-:first@2,crash.pass2:once@3,seed=7")
    assert plan.seed == 7
    # key matching: only keys containing the match substring count
    assert plan.should_fire("spill_write", "tokens-00000.npz") is None
    assert plan.should_fire("spill_write", "pairs-000-00000.npz")
    assert plan.should_fire("spill_write", "pairs-001-00000.npz")
    assert plan.should_fire("spill_write", "pairs-002-00000.npz") is None
    assert plan.should_fire("crash.pass2") is None
    assert plan.should_fire("crash.pass2") is None
    assert plan.should_fire("crash.pass2") is not None
    assert plan.counters() == {"spill_write": 2, "crash.pass2": 1}

    # probabilistic rules replay identically under the same seed
    seq = [faults.parse_plan("x:p=0.5,seed=3").should_fire("x") is not None
           for _ in range(20)]
    seq2 = []
    p2 = faults.parse_plan("x:p=0.5,seed=3")
    for _ in range(20):
        seq2.append(p2.should_fire("x") is not None)
    assert any(seq2) and not all(seq2)
    # fresh per-call plans all see the same first draw; one plan's stream
    # is the deterministic sequence
    plan_a = faults.parse_plan("x:p=0.5,seed=3")
    got_a = [plan_a.should_fire("x") is not None for _ in range(20)]
    assert got_a == seq2


def test_plan_parsing_sleep_modifier():
    p = faults.parse_plan("score.hang:sleep=0.5")
    spec = p.should_fire("score.hang")
    assert spec is not None and spec.sleep_s == 0.5 and spec.mode == "always"
    p2 = faults.parse_plan("score.hang:once@2:sleep=1.5")
    assert p2.should_fire("score.hang") is None
    spec2 = p2.should_fire("score.hang")
    assert spec2 is not None and spec2.sleep_s == 1.5
    with pytest.raises(ValueError):
        faults.parse_plan("site:not-a-rule")


def test_env_var_installs_plan(monkeypatch):
    monkeypatch.setenv("TPU_IR_FAULTS", "some_site:once@1")
    faults.clear()
    assert faults.should_fire("some_site") is not None
    assert faults.should_fire("some_site") is None
    faults.clear()


def test_disabled_plan_is_inert():
    assert faults.active() is None
    assert faults.should_fire("anything") is None


# ---------------------------------------------------------------------------
# fault class 1: failed spill writes -> supervised retry
# ---------------------------------------------------------------------------


def test_spill_write_failures_retried_to_identical_artifacts(
        tmp_path, monkeypatch, ref):
    # pins the LEGACY spill retry accounting (pairs- batch spills +
    # token spills); the radix default (ISSUE 13) has its own fault-site
    # coverage below — request the legacy path explicitly
    corpus, ref_dir = ref
    out = str(tmp_path / "idx")
    monkeypatch.setenv("TPU_IR_RADIX_BUCKETS", "0")
    small_chunks(monkeypatch)
    # fail the first 2 pair-spill writes AND the first token-spill write:
    # the supervised retry must absorb all of them
    faults.install(faults.parse_plan(
        "spill_write@pairs-:first@2,spill_write@tokens-:first@1"))
    build_index_streaming([corpus], out, **BUILD_KW)
    assert recovery_counters().get("retries") == 3
    assert verify_index(out)["ok"]
    assert_identical(out, ref_dir)


def test_spill_write_exhaustion_is_structured_build_error(
        tmp_path, monkeypatch, ref):
    # legacy path: token spills only exist there (radix packs lengths
    # into the pass-1 manifest instead)
    corpus, _ = ref
    out = str(tmp_path / "idx")
    monkeypatch.setenv("TPU_IR_RADIX_BUCKETS", "0")
    small_chunks(monkeypatch)
    faults.install(faults.parse_plan("spill_write@tokens-:first@99"))
    with pytest.raises(faults.BuildError) as ei:
        build_index_streaming([corpus], out, **BUILD_KW)
    assert ei.value.stage.startswith("write:tokens-")
    assert ei.value.attempts == faults.SPILL_RETRY.max_attempts
    assert recovery_counters().get("retry_exhausted") == 1


def test_part_write_failures_retried(tmp_path, ref):
    """Part-file writes ride the same supervised retry as spills
    (RUNBOOK §7 row 1) — the policy lives inside savez_atomic, so every
    writer inherits it."""
    from tpu_ir.index import build_index

    corpus, _ = ref
    out = str(tmp_path / "idx")
    faults.install(faults.parse_plan("spill_write@part-:first@2"))
    build_index([corpus], out, num_shards=3, chargram_ks=[2])
    assert recovery_counters().get("retries") == 2
    assert verify_index(out)["ok"]


def test_truncated_token_spill_is_structured_then_recovers(
        tmp_path, monkeypatch, ref):
    """artifact_truncate corrupts a token spill AFTER its CRC was taken
    (pre-rename), so the in-run read fails as ONE structured
    IntegrityError and the restart's manifest check discards the state
    and re-tokenizes to a byte-identical index."""
    corpus, ref_dir = ref
    out = str(tmp_path / "idx")
    monkeypatch.setenv("TPU_IR_RADIX_BUCKETS", "0")  # token spills: legacy
    small_chunks(monkeypatch)
    faults.install(faults.parse_plan("artifact_truncate@tokens-:once@2"))
    with pytest.raises(faults.IntegrityError) as ei:
        build_index_streaming([corpus], out, **BUILD_KW)
    assert "tokens-00001" in ei.value.path
    faults.clear()
    build_index_streaming([corpus], out, **BUILD_KW)
    assert recovery_counters().get("spill_integrity_discards") >= 1
    assert verify_index(out)["ok"]
    assert_identical(out, ref_dir)


# ---------------------------------------------------------------------------
# fault class 2: corrupt artifacts -> quarantine / integrity errors
# ---------------------------------------------------------------------------


def test_corrupt_part_quarantined_and_single_shard_rebuilt(
        tmp_path, monkeypatch, ref):
    """A corrupt part file on resume is quarantined and ONLY that shard is
    rebuilt from its surviving spills — never the whole index."""
    corpus, ref_dir = ref
    out = str(tmp_path / "idx")
    small_chunks(monkeypatch)
    # die after pass 3 wrote shards 0 and 1
    faults.install(faults.parse_plan("crash.pass3:once@2"))
    with pytest.raises(faults.InjectedCrash):
        build_index_streaming([corpus], out, **BUILD_KW)
    faults.clear()
    assert os.path.exists(os.path.join(out, fmt.part_name(1)))

    # shard 0's part rots on disk (truncation)
    part0 = os.path.join(out, fmt.part_name(0))
    with open(part0, "r+b") as f:
        f.truncate(os.path.getsize(part0) // 2)

    forbid_tokenizer(monkeypatch)
    real_reduce = streaming.reduce_shard_spills
    rebuilt = []
    monkeypatch.setattr(
        streaming, "reduce_shard_spills",
        lambda spill, idx, row, *a, **kw: (
            rebuilt.append(row), real_reduce(spill, idx, row, *a, **kw))[1])
    build_index_streaming([corpus], out, **BUILD_KW)
    # shard 0 (corrupt) and shard 2 (never written) rebuilt; shard 1 reused
    assert rebuilt == [0, 2]
    assert recovery_counters().get("quarantined") == 1
    assert os.path.exists(
        os.path.join(out, fmt.QUARANTINE_DIR, fmt.part_name(0)))
    assert verify_index(out)["ok"]
    assert_identical(out, ref_dir)


def test_corrupt_part_on_finished_index_is_integrity_error(tmp_path, ref):
    """After a build certifies its checksums, byte corruption surfaces at
    Scorer.load as ONE structured IntegrityError naming the file."""
    corpus, _ = ref
    out = str(tmp_path / "idx")
    build_index_streaming([corpus], out, **BUILD_KW)
    target = os.path.join(out, fmt.part_name(1))
    _flip_byte(target)
    with pytest.raises(faults.IntegrityError) as ei:
        Scorer.load(out)
    assert ei.value.path == target
    # `tpu-ir verify` reports the same structured failure
    with pytest.raises(faults.IntegrityError):
        verify_index(out)


def test_corrupt_side_artifact_is_integrity_error(tmp_path, ref):
    corpus, _ = ref
    out = str(tmp_path / "idx")
    build_index_streaming([corpus], out, **BUILD_KW)
    _flip_byte(os.path.join(out, fmt.DOCLEN))
    with pytest.raises(faults.IntegrityError) as ei:
        Scorer.load(out)
    assert ei.value.path.endswith(fmt.DOCLEN)


def test_corrupt_token_spill_discards_resume(tmp_path, monkeypatch, ref):
    """A token spill failing its manifest CRC cannot be repaired without
    re-tokenizing: the whole pass-1 state is discarded and the rebuild
    still converges to byte-identical artifacts."""
    corpus, ref_dir = ref
    out = str(tmp_path / "idx")
    monkeypatch.setenv("TPU_IR_RADIX_BUCKETS", "0")  # token spills: legacy
    small_chunks(monkeypatch)
    faults.install(faults.parse_plan("crash.pass2:once@2"))
    with pytest.raises(faults.InjectedCrash):
        build_index_streaming([corpus], out, **BUILD_KW)
    faults.clear()
    _flip_byte(os.path.join(out, "_spill", "tokens-00001.npz"))

    tokenized = {"n": 0}
    real_tok = streaming.make_chunked_tokenizer

    def counting(*a, **kw):
        tokenized["n"] += 1
        return real_tok(*a, **kw)

    monkeypatch.setattr(streaming, "make_chunked_tokenizer", counting)
    build_index_streaming([corpus], out, **BUILD_KW)
    assert tokenized["n"] == 1  # resume rejected -> re-tokenized
    assert recovery_counters().get("spill_integrity_discards") >= 1
    assert verify_index(out)["ok"]
    assert_identical(out, ref_dir)


def test_corrupt_pair_spill_recomputes_only_that_batch(
        tmp_path, monkeypatch, ref):
    corpus, ref_dir = ref
    out = str(tmp_path / "idx")
    small_chunks(monkeypatch)
    faults.install(faults.parse_plan("crash.pass3:once@1"))
    with pytest.raises(faults.InjectedCrash):
        build_index_streaming([corpus], out, **BUILD_KW)
    faults.clear()
    spill = os.path.join(out, "_spill")
    with np.load(os.path.join(spill, PASS1_MANIFEST)) as z:
        n_batches = int(z["n_batches"])
    assert n_batches >= 3
    _flip_byte(os.path.join(spill, "pairs-001-00001.npz"))

    forbid_tokenizer(monkeypatch)
    real_postings = streaming.build_postings_packed_jit
    recomputed = {"n": 0}
    monkeypatch.setattr(
        streaming, "build_postings_packed_jit",
        lambda *a, **kw: (recomputed.__setitem__("n", recomputed["n"] + 1),
                          real_postings(*a, **kw))[1])
    build_index_streaming([corpus], out, **BUILD_KW)
    assert recomputed["n"] == 1  # only the corrupt batch re-ran
    assert recovery_counters().get("spill_integrity_discards") == 1
    assert verify_index(out)["ok"]
    assert_identical(out, ref_dir)


def test_corrupt_manifest_rejected_full_rebuild(tmp_path, monkeypatch, ref):
    """Garbage where pass1.npz should be must be rejected (fresh build),
    never trusted or tracebacked."""
    corpus, ref_dir = ref
    out = str(tmp_path / "idx")
    small_chunks(monkeypatch)
    faults.install(faults.parse_plan("crash.pass2:once@2"))
    with pytest.raises(faults.InjectedCrash):
        build_index_streaming([corpus], out, **BUILD_KW)
    faults.clear()
    manifest = os.path.join(out, "_spill", PASS1_MANIFEST)
    with open(manifest, "wb") as f:
        f.write(b"this is not an npz file at all")
    build_index_streaming([corpus], out, **BUILD_KW)
    assert verify_index(out)["ok"]
    assert_identical(out, ref_dir)


# ---------------------------------------------------------------------------
# fault class 3: mid-pass process death -> resume to identical artifacts
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("site,rule", [
    ("crash.pass1", "once@2"),
    ("crash.pass2", "once@2"),
    ("crash.pass3", "once@2"),
])
def test_mid_pass_death_recovers_byte_identical(tmp_path, monkeypatch, ref,
                                                site, rule):
    corpus, ref_dir = ref
    out = str(tmp_path / "idx")
    small_chunks(monkeypatch)
    faults.install(faults.parse_plan(f"{site}:{rule}"))
    with pytest.raises(faults.InjectedCrash):
        build_index_streaming([corpus], out, **BUILD_KW)
    faults.clear()
    if site != "crash.pass1":
        # pass-1 completed before the death: the restart must not
        # re-tokenize (a pass-1 death dies before the manifest, so a
        # fresh tokenize IS the correct recovery there)
        forbid_tokenizer(monkeypatch)
    else:
        small_chunks(monkeypatch)
    build_index_streaming([corpus], out, **BUILD_KW)
    assert verify_index(out)["ok"]
    assert_identical(out, ref_dir)


def test_injected_crash_is_not_swallowed_by_retry():
    """InjectedCrash must behave like a real SIGKILL: the retry supervisor
    (and any `except Exception` recovery code) cannot absorb it."""
    def dies():
        raise faults.InjectedCrash("boom")
    with pytest.raises(faults.InjectedCrash):
        faults.run_with_retry(dies, stage="x",
                              retry_on=(OSError, RuntimeError))
    assert not isinstance(faults.InjectedCrash("x"), Exception)


# ---------------------------------------------------------------------------
# fault class 4: all_to_all capacity overflow -> policy retry / BuildError
# ---------------------------------------------------------------------------


def _synth_occurrences(n_tok=4000, n_docs=64, vocab=300, seed=0):
    rng = np.random.default_rng(seed)
    flat_term = rng.integers(0, vocab, n_tok).astype(np.int32)
    flat_doc = rng.integers(1, n_docs + 1, n_tok).astype(np.int32)
    docnos = np.arange(1, n_docs + 1, dtype=np.int32)
    return flat_term, flat_doc, docnos, vocab, n_docs


def test_overflow_retry_recovers():
    from tpu_ir.parallel import make_mesh, sharded_build_postings
    from tpu_ir.parallel.sharded_build import deal_occurrences

    ft, fd, docnos, vocab, ndocs = _synth_occurrences()
    t, d, dps = deal_occurrences(ft, fd, docnos, 8)
    faults.install(faults.parse_plan("shuffle_overflow:first@2"))
    out = sharded_build_postings(t, d, dps, vocab_size=vocab,
                                 total_docs=ndocs, mesh=make_mesh(8))
    faults.clear()
    assert recovery_counters().get("overflow_retries") == 2
    # the psum'd doc counter still reports the real corpus
    assert int(np.asarray(out.num_docs)[0]) == ndocs
    # and the recovered result matches a fault-free dispatch
    clean = sharded_build_postings(t, d, dps, vocab_size=vocab,
                                   total_docs=ndocs, mesh=make_mesh(8))
    np.testing.assert_array_equal(np.asarray(out.df), np.asarray(clean.df))


def test_overflow_exhaustion_is_structured_build_error():
    from tpu_ir.parallel import make_mesh, sharded_build_postings
    from tpu_ir.parallel.sharded_build import deal_occurrences

    ft, fd, docnos, vocab, ndocs = _synth_occurrences()
    t, d, dps = deal_occurrences(ft, fd, docnos, 8)
    faults.install(faults.parse_plan("shuffle_overflow:always"))
    with pytest.raises(faults.BuildError) as ei:
        sharded_build_postings(t, d, dps, vocab_size=vocab,
                               total_docs=ndocs, mesh=make_mesh(8))
    assert ei.value.stage == "all_to_all_shuffle"
    assert "overflow" in str(ei.value)


# ---------------------------------------------------------------------------
# fault class 5: slow/hung or device-lost dispatch -> degraded serving
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def served(ref):
    corpus, ref_dir = ref
    return Scorer.load(ref_dir)


def test_hung_dispatch_degrades_within_deadline(served):
    s = served
    faults.install(faults.FaultPlan().add("score.hang", "always",
                                          sleep_s=5.0))
    s.deadline_s = 0.25
    try:
        q = s.analyze_queries(["salmon fishing", "stock market"])
        t0 = time.perf_counter()
        scores, docnos, degraded = s.topk_tagged(q, k=5, scoring="bm25")
        elapsed = time.perf_counter() - t0
    finally:
        s.deadline_s = None
        faults.clear()
    assert elapsed < 3.0, "deadline did not bound the hung dispatch"
    # the per-request tagged return is THE degradation surface (the
    # single-threaded degraded_last alias is gone — ISSUE 9)
    assert degraded
    assert recovery_counters().get("deadline_expired") == 1
    assert recovery_counters().get("degraded_batches") == 1
    assert (docnos[0] > 0).any() and (docnos[1] > 0).any()
    # degraded results are real rankings: same docs as the primary path
    ps, pd, degraded2 = s.topk_tagged(q, k=5, scoring="bm25")
    assert not degraded2
    np.testing.assert_array_equal(docnos, pd)
    np.testing.assert_allclose(scores, ps, rtol=1e-4)


def test_device_loss_degrades_and_tags_results(served):
    s = served
    faults.install(faults.parse_plan("score.device_loss:once@1"))
    try:
        res = s.search_batch(["salmon fishing"], k=5, scoring="tfidf")
    finally:
        faults.clear()
    assert res[0].degraded
    assert len(res[0]) > 0
    assert recovery_counters().get("device_loss") == 1
    # next batch is healthy again and tagged accordingly
    res2 = s.search_batch(["salmon fishing"], k=5, scoring="tfidf")
    assert not res2[0].degraded
    assert [k for k, _ in res2[0]] == [k for k, _ in res[0]]


def test_rerank_degrades_to_host_bm25(served):
    s = served
    faults.install(faults.parse_plan("score.device_loss:once@1"))
    try:
        scores, docnos, degraded = s.rerank_topk_tagged(
            s.analyze_queries(["salmon fishing"]), k=5, candidates=50)
    finally:
        faults.clear()
    assert degraded
    assert (docnos > 0).any()
    assert recovery_counters().get("degraded_batches") == 1


def test_deadline_fails_fast_once_abandoned_cap_hit():
    """A permanently hung device must not grow one blocked thread per
    query: past _ABANDONED_CAP live abandoned dispatches, deadlined calls
    fail fast without spawning or waiting."""
    import threading

    ev = threading.Event()
    try:
        for _ in range(faults._ABANDONED_CAP):
            with pytest.raises(faults.ScoreDeadlineExceeded):
                faults.run_with_deadline(lambda: ev.wait(30), 0.05)
        t0 = time.perf_counter()
        with pytest.raises(faults.ScoreDeadlineExceeded):
            faults.run_with_deadline(lambda: ev.wait(30), 10.0)
        assert time.perf_counter() - t0 < 1.0, "did not fail fast"
    finally:
        ev.set()
        for t in faults._abandoned:
            t.join(5)
        faults._abandoned.clear()


def test_cache_fast_path_lazy_pairs_verified(tmp_path, ref):
    """The serving-cache fast path defers the shard read; when something
    later needs the CSR columns, the parts are checksum-verified first —
    rot since cache time surfaces as IntegrityError, not a zip traceback."""
    corpus, _ = ref
    out = str(tmp_path / "idx")
    build_index_streaming([corpus], out, **BUILD_KW)
    Scorer.load(out, layout="sparse")       # builds + persists the cache
    s = Scorer.load(out, layout="sparse")   # cache hit: no shard read yet
    assert s._pairs_cols is None
    _flip_byte(os.path.join(out, fmt.part_name(0)))
    with pytest.raises(faults.IntegrityError):
        s._pairs


def test_no_deadline_no_plan_takes_primary_path(served):
    s = served
    q = s.analyze_queries(["salmon fishing"])
    scores, docnos, degraded = s.topk_tagged(q, k=5)
    assert not degraded
    assert (docnos > 0).any()


def test_concurrent_queries_tag_exactly_one_degraded(served):
    """The degraded_last race regression (ISSUE 2 satellite): two queries
    running CONCURRENTLY with exactly one injected device loss must come
    back with exactly one tagged degraded — the per-request flag rides
    the return path (topk_tagged -> SearchResult.degraded), so one
    thread's fallback can never mis-tag the other thread's result."""
    import threading

    s = served
    texts = ["salmon fishing", "stock market"]
    clean = [[k for k, _ in r]
             for r in s.search_batch(texts, k=5, scoring="bm25")]
    faults.install(faults.parse_plan("score.device_loss:once@1"))
    results = [None, None]
    barrier = threading.Barrier(2)

    def go(i: int) -> None:
        barrier.wait()
        results[i] = s.search_batch([texts[i]], k=5, scoring="bm25")[0]

    try:
        threads = [threading.Thread(target=go, args=(i,), daemon=True)
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert all(not t.is_alive() for t in threads)
    finally:
        faults.clear()
    assert all(r is not None for r in results)
    flags = [r.degraded for r in results]
    assert sum(flags) == 1, f"exactly one must degrade, got {flags}"
    assert recovery_counters().get("device_loss") == 1
    # BOTH results are the correct ranking regardless of which degraded
    for got, want in zip(results, clean):
        assert [k for k, _ in got] == want


@pytest.mark.parametrize("layout", ["sparse", "sharded"])
@pytest.mark.parametrize("op", ["topk", "rerank"])
def test_degraded_fallback_matrix(ref, layout, op):
    """The host-CPU degraded fallback across the tiered and sharded
    layouts (PR 1 pinned it on the dense path only). Every (layout, op)
    cell must: fire the injected device loss, tag the batch degraded,
    and answer with the host model's ranking. The sharded rerank cell is
    the one this matrix originally exposed — its dispatch bypassed
    _topk_device, so no injection site (and no real device loss
    detection coverage) existed on that path."""
    _, ref_dir = ref
    s = Scorer.load(ref_dir, layout=layout)
    q = s.analyze_queries(["salmon fishing", "stock market"])

    def run():
        if op == "topk":
            return s.topk_tagged(q, k=5, scoring="bm25")
        return s.rerank_topk_tagged(q, k=5, candidates=20)

    cs, cd, cdeg = run()
    assert not cdeg and (cd > 0).any()
    faults.install(faults.parse_plan("score.device_loss:once@1"))
    try:
        ds, dd, ddeg = run()
    finally:
        faults.clear()
    assert ddeg, f"{layout}/{op}: injected device loss did not degrade"
    assert recovery_counters().get("device_loss") == 1
    assert recovery_counters().get("degraded_batches") == 1
    assert (dd > 0).any()
    # the degraded answer IS the host model's ranking (rerank falls back
    # to single-stage host BM25 by contract)
    hs, hd = s._topk_host(q, 5, "bm25")
    np.testing.assert_array_equal(np.asarray(dd), hd)


def test_hot_only_dispatch_is_tagged_partial(ref):
    """The overload ladder's hot-tier-only level on a full Scorer: a
    hot_only dispatch must never be mistaken for full service — it runs
    the device path (not degraded) and the serving frontend tags its
    level. Here: results are a subset of the full model's contributions
    (scores bounded above by the full scores)."""
    _, ref_dir = ref
    s = Scorer.load(ref_dir, layout="sparse")
    q = s.analyze_queries(["salmon fishing river"])
    fs, fd, fdeg = s.topk_tagged(q, k=5, scoring="bm25")
    hs, hd, hdeg = s.topk_tagged(q, k=5, scoring="bm25", hot_only=True)
    assert not fdeg and not hdeg
    # hot-only is a lower bound on the full model: its best score cannot
    # exceed the full model's best
    assert float(hs.max()) <= float(fs.max()) + 1e-5


# ---------------------------------------------------------------------------
# quarantine retention (bounded .quarantine/ growth)
# ---------------------------------------------------------------------------


def test_quarantine_retention_keeps_last_k(tmp_path):
    d = str(tmp_path)
    for i in range(7):
        with open(os.path.join(d, f"part-{i:05d}.npz"), "wb") as f:
            f.write(b"corrupt" + bytes([i]))
        fmt.quarantine(d, f"part-{i:05d}.npz", keep=4)
        time.sleep(0.002)  # distinct quarantine stamps
    qdir = os.path.join(d, fmt.QUARANTINE_DIR)
    kept = sorted(os.listdir(qdir))
    # the 4 most recently quarantined survive; the 3 oldest evicted
    assert kept == [f"part-{i:05d}.npz" for i in (3, 4, 5, 6)]
    assert recovery_counters().get("quarantined") == 7
    assert recovery_counters().get("quarantine_evicted") == 3


def test_quarantine_retention_env_knob(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_IR_QUARANTINE_KEEP", "2")
    d = str(tmp_path)
    for i in range(4):
        with open(os.path.join(d, f"doc_len-{i}.npy"), "wb") as f:
            f.write(b"x")
        fmt.quarantine(d, f"doc_len-{i}.npy")
        time.sleep(0.002)
    assert len(os.listdir(os.path.join(d, fmt.QUARANTINE_DIR))) == 2
    assert recovery_counters().get("quarantine_evicted") == 2


# ---------------------------------------------------------------------------
# end-to-end: the CLI surfaces structured errors, never tracebacks
# ---------------------------------------------------------------------------


def test_cli_surfaces_integrity_error_cleanly(tmp_path, ref, capsys):
    from tpu_ir.cli import main

    corpus, _ = ref
    out = str(tmp_path / "idx")
    build_index_streaming([corpus], out, **BUILD_KW)
    _flip_byte(os.path.join(out, fmt.part_name(0)))
    rc = main(["verify", out])
    assert rc == 1
    err = capsys.readouterr().err
    assert "integrity" in err.lower()
    assert fmt.part_name(0) in err


def test_cli_faults_flag_surfaces_build_error(tmp_path, ref, capsys,
                                              monkeypatch):
    """--faults installs the plan and retry exhaustion reaches the user as
    ONE clean structured error line, not a traceback."""
    from tpu_ir.cli import main

    corpus, _ = ref
    out = str(tmp_path / "idx")
    monkeypatch.setenv("TPU_IR_RADIX_BUCKETS", "0")  # token spills: legacy
    rc = main(["index", corpus, out, "--streaming", "--shards", "2",
               "--no-chargrams", "--faults",
               "spill_write@tokens-:first@99"])
    assert rc == 1
    err = capsys.readouterr().err
    assert "error: build stage" in err and "write:tokens-" in err


def test_inspect_reports_corrupt_artifact_cleanly(tmp_path, ref):
    from tpu_ir.index.artifacts import inspect_path

    corpus, _ = ref
    out = str(tmp_path / "idx")
    build_index_streaming([corpus], out, **BUILD_KW)
    part = os.path.join(out, fmt.part_name(0))
    with open(part, "r+b") as f:
        f.truncate(os.path.getsize(part) // 2)
    lines = list(inspect_path(part))
    assert any("CORRUPT" in ln for ln in lines)


# ---------------------------------------------------------------------------
# radix-bucketed spill fault sites (ISSUE 11): the pass-1 rpairs spills
# and pass-2 per-bucket pair spills ride the SAME spill_write /
# artifact_truncate sites as every other atomic artifact, keyed by their
# new file names — so an operator plan can target exactly them, and
# every fault class keeps its recovery contract at bucket scope.
# ---------------------------------------------------------------------------


def test_radix_spill_write_failures_retried_to_identical(
        tmp_path, ref):
    """Transient write failures on BUCKETED spill files (pass-1 rpairs
    AND pass-2 per-bucket pairs) retry under SPILL_RETRY and converge on
    byte-identical artifacts."""
    corpus, ref_dir = ref
    out = str(tmp_path / "idx")
    faults.install(faults.parse_plan(
        "spill_write@rpairs-:first@2,spill_write@pairs-:first@1"))
    build_index_streaming([corpus], out, radix_buckets=4, **BUILD_KW)
    assert recovery_counters().get("retries") >= 3
    assert_identical(out, ref_dir)


def test_radix_spill_write_exhaustion_is_structured(tmp_path, ref):
    corpus, _ = ref
    out = str(tmp_path / "idx")
    faults.install(faults.parse_plan("spill_write@rpairs-:first@99"))
    with pytest.raises(faults.BuildError) as ei:
        build_index_streaming([corpus], out, radix_buckets=4, **BUILD_KW)
    assert ei.value.stage.startswith("write:rpairs-")


def test_truncated_rpairs_spill_discards_pass1_state(
        tmp_path, monkeypatch, ref):
    """artifact_truncate corrupts an rpairs spill AFTER its CRC was
    recorded: the resume's manifest check catches the mismatch, discards
    the whole pass-1 state (a bucketed pair spill cannot be rebuilt
    without re-tokenizing) and the rebuild converges."""
    corpus, ref_dir = ref
    out = str(tmp_path / "idx")
    small_chunks(monkeypatch)
    faults.install(faults.parse_plan(
        "artifact_truncate@rpairs-:once@3,crash.pass2:once@1"))
    with pytest.raises(faults.InjectedCrash):
        build_index_streaming([corpus], out, radix_buckets=4, **BUILD_KW)
    faults.clear()
    tokenized = {"n": 0}

    def counting(*a, **kw):
        tokenized["n"] += 1
        return _REAL_TOKENIZER(paths=a[0], k=kw.get("k", 1),
                               chunk_bytes=400,
                               with_text=kw.get("with_text", False))

    monkeypatch.setattr(streaming, "make_chunked_tokenizer", counting)
    build_index_streaming([corpus], out, radix_buckets=4, **BUILD_KW)
    assert tokenized["n"] == 1
    assert recovery_counters().get("spill_integrity_discards") >= 1
    assert_identical(out, ref_dir)


def test_truncated_bucket_pair_spill_quarantines_only_that_bucket(
        tmp_path, monkeypatch, ref):
    """artifact_truncate on a PASS-2 bucket spill: resume validation
    deletes only that bucket's per-shard spills and recomputes the one
    bucket — pass 1 untouched, every other bucket untouched."""
    corpus, ref_dir = ref
    out = str(tmp_path / "idx")
    buckets = 5
    faults.install(faults.parse_plan(
        "artifact_truncate@pairs-001-00003:always,crash.pass3:once@1"))
    with pytest.raises(faults.InjectedCrash):
        build_index_streaming([corpus], out, radix_buckets=buckets,
                              **BUILD_KW)
    faults.clear()
    forbid_tokenizer(monkeypatch)
    calls = {"n": 0}
    real = streaming.build_postings_packed_jit
    monkeypatch.setattr(
        streaming, "build_postings_packed_jit",
        lambda *a, **kw: (calls.__setitem__("n", calls["n"] + 1),
                          real(*a, **kw))[1])
    build_index_streaming([corpus], out, radix_buckets=buckets,
                          **BUILD_KW)
    assert calls["n"] == 1  # only bucket 3 reduced again
    assert recovery_counters().get("spill_integrity_discards") >= 1
    assert_identical(out, ref_dir)


def test_radix_mid_pass_death_matrix(tmp_path, monkeypatch, ref):
    """SIGKILL-equivalent deaths in every radix pass recover
    byte-identical on restart (the legacy matrix, at bucket scope)."""
    corpus, ref_dir = ref
    small_chunks(monkeypatch)
    for i, (site, rule) in enumerate([("crash.pass1", "once@2"),
                                      ("crash.pass2", "once@2"),
                                      ("crash.pass3", "once@2")]):
        out = str(tmp_path / f"idx{i}")
        faults.install(faults.parse_plan(f"{site}:{rule}"))
        with pytest.raises(faults.InjectedCrash):
            build_index_streaming([corpus], out, radix_buckets=4,
                                  **BUILD_KW)
        faults.clear()
        build_index_streaming([corpus], out, radix_buckets=4, **BUILD_KW)
        assert_identical(out, ref_dir)
        assert verify_index(out)["ok"]
