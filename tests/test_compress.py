"""Compressed quantized arena acceptance suite (ISSUE 20): codec fuzz
round-trips, `migrate-index --compress/--decompress` (byte-identical
rollback, idempotence, SIGKILL-mid-migrate), raw-vs-compressed serving
bit-parity across scoring modes and block-max regimes, the memory-lean
doc-range decode, the v7 serving-cache key (section-dtype signature),
lossy-int8 loudness, and the doctor/verify compression readouts."""

import json
import os

import numpy as np
import pytest

import tpu_ir.faults as faults
from tpu_ir.cli import main
from tpu_ir.index import build_index
from tpu_ir.index import compress as comp
from tpu_ir.index import format as fmt
from tpu_ir.index.migrate import migrate_index
from tpu_ir.index.verify import verify_index
from tpu_ir.search import Scorer
from tpu_ir.utils.report import recovery_counters

WORDS = ("salmon fishing river bears honey quick brown fox lazy dog "
         "market investor asset bond stock season rain forest".split())

QUERIES = ("salmon fishing", "honey bears river", "stock market asset",
           "quick brown fox", "rain")


@pytest.fixture(autouse=True)
def _clean_state():
    faults.clear()
    recovery_counters().reset()
    fmt.reset_read_bytes()
    yield
    faults.clear()
    recovery_counters().reset()
    fmt.reset_read_bytes(arm=False)


def write_corpus(path, n_docs=90):
    body = []
    for i in range(n_docs):
        text = " ".join(WORDS[(i + j) % len(WORDS)]
                        for j in range(3 + (i % 7)))
        body.append(f"<DOC>\n<DOCNO> D-{i:04d} </DOCNO>\n<TEXT>\n"
                    f"{text}\n</TEXT>\n</DOC>\n")
    path.write_text("".join(body))
    return str(path)


def build(corpus, out):
    build_index([corpus], out, k=1, num_shards=3,
                compute_chargrams=False)


def results(idx, layout="sparse", scoring="tfidf"):
    s = Scorer.load(idx, layout=layout)
    return [s.search(q, k=10, scoring=scoring) for q in QUERIES]


def assert_bit_identical(a, b, ctx=""):
    """Same docnos in the same order AND the same float32 score BITS."""
    for qa, qb in zip(a, b):
        assert [r[0] for r in qa] == [r[0] for r in qb], ctx
        sa = np.array([r[1] for r in qa], np.float32)
        sb = np.array([r[1] for r in qb], np.float32)
        assert sa.tobytes() == sb.tobytes(), ctx


def random_shard(rng, *, terms=30, num_docs=3000, max_tf=9):
    """A raw shard dict in the builders' canonical impact order."""
    term_ids, df_l, docs_l, tfs_l = [], [], [], []
    for t in range(terms):
        n = int(rng.integers(1, min(num_docs, 200)))
        d = np.sort(rng.choice(np.arange(1, num_docs + 1), size=n,
                               replace=False))
        tf = rng.integers(1, max_tf + 1, size=n)
        order = np.lexsort((d, -tf))
        term_ids.append(t * 3)
        df_l.append(n)
        docs_l.append(d[order])
        tfs_l.append(tf[order])
    df = np.array(df_l, np.int64)
    return {
        "term_ids": np.array(term_ids, np.int32),
        "df": df.astype(np.int32),
        "indptr": np.concatenate([[0], np.cumsum(df)]).astype(np.int64),
        "pair_doc": np.concatenate(docs_l).astype(np.int32),
        "pair_tf": np.concatenate(tfs_l).astype(np.int32),
    }


# ---------------------------------------------------------------------------
# codec unit behavior
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
@pytest.mark.parametrize("tf_dtype", ["int8", "bf16"])
def test_codec_fuzz_roundtrip(seed, tf_dtype):
    """encode -> decode reproduces the raw arrays byte-for-byte (values
    AND dtypes), across random shapes, both lossless tf modes, and
    block widths that do / don't divide the doc axis."""
    rng = np.random.default_rng(seed)
    z = random_shard(rng, terms=10 + seed * 7,
                     num_docs=500 + seed * 777)
    enc = comp.encode_shard(z, num_docs=500 + seed * 777,
                            tf_dtype=tf_dtype,
                            block_width=64 if seed % 2 else None)
    assert comp.is_compressed(enc)
    dec = comp.decode_shard(enc)
    for k in ("term_ids", "df", "indptr", "pair_doc", "pair_tf"):
        assert np.asarray(dec[k]).dtype == z[k].dtype, k
        assert np.array_equal(dec[k], z[k]), k
    info = comp.shard_info(enc)
    assert not info["tf_lossy"]


def test_codec_refuses_noncanonical_order():
    rng = np.random.default_rng(9)
    z = random_shard(rng)
    bad = dict(z, pair_doc=z["pair_doc"][::-1].copy())
    with pytest.raises(comp.CompressError):
        comp.encode_shard(bad, num_docs=3000)


def test_lossy_int8_floor_quantizes_rank_safe():
    """>256 distinct tfs under int8: served tf stays in (0, raw tf]
    (block-max bounds remain upper bounds), per-term order stays
    canonical wrt the QUANTIZED tfs, and the shard is stamped lossy."""
    rng = np.random.default_rng(3)
    z = random_shard(rng, terms=40, num_docs=5000, max_tf=2000)
    enc = comp.encode_shard(z, num_docs=5000, tf_dtype="int8")
    info = comp.shard_info(enc)
    assert info["tf_lossy"]
    dec = comp.decode_shard(enc)
    assert np.array_equal(dec["df"], z["df"])
    ip = z["indptr"]
    qd, qt = dec["pair_doc"], dec["pair_tf"]
    assert len(np.unique(qt)) <= 256
    for i in range(len(z["df"])):
        lo, hi = ip[i], ip[i + 1]
        assert set(qd[lo:hi].tolist()) == set(
            z["pair_doc"][lo:hi].tolist())
        raw = dict(zip(z["pair_doc"][lo:hi].tolist(),
                       z["pair_tf"][lo:hi].tolist()))
        for d_, q_ in zip(qd[lo:hi].tolist(), qt[lo:hi].tolist()):
            assert 0 < q_ <= raw[d_]
        seg_tf, seg_doc = qt[lo:hi], qd[lo:hi]
        assert (np.diff(seg_tf) <= 0).all()
        ties = np.diff(seg_tf) == 0
        assert (np.diff(seg_doc)[ties] > 0).all()


def test_doc_range_decode_skips_payload():
    """Lean decode: out-of-range grid groups come back as (0, 0) dead
    slots WITHOUT their payload bytes being counted, and in-range
    postings are byte-identical to the full decode."""
    rng = np.random.default_rng(5)
    num_docs = 4000
    # DENSE terms: grid groups must win over flat runs for block
    # skipping to exist at all (sparse random terms go flat)
    term_ids, df_l, docs_l, tfs_l = [], [], [], []
    for t in range(20):
        n = int(rng.integers(1000, 3500))
        d = np.sort(rng.choice(np.arange(1, num_docs + 1), size=n,
                               replace=False))
        tf = rng.integers(1, 10, size=n)
        order = np.lexsort((d, -tf))
        term_ids.append(t)
        df_l.append(n)
        docs_l.append(d[order])
        tfs_l.append(tf[order])
    dfa = np.array(df_l, np.int64)
    z = {
        "term_ids": np.array(term_ids, np.int32),
        "df": dfa.astype(np.int32),
        "indptr": np.concatenate([[0], np.cumsum(dfa)]).astype(np.int64),
        "pair_doc": np.concatenate(docs_l).astype(np.int32),
        "pair_tf": np.concatenate(tfs_l).astype(np.int32),
    }
    enc = comp.encode_shard(z, num_docs=num_docs, block_width=64)
    full = comp.decode_shard(enc)
    from tpu_ir.obs import get_registry

    reg = get_registry()
    before = reg.get("decode.bytes")
    lo, hi = 1, 200  # half-open, ~5% of the doc axis
    lean = comp.decode_shard(enc, doc_range=(lo, hi))
    touched = reg.get("decode.bytes") - before
    skipped = reg.get("decode.bytes_skipped")
    assert skipped > touched  # most payload never read
    # dead slots re-sort to their term runs' ends, so positions shift
    # vs the full decode — the contract is on the (term, doc, tf)
    # TRIPLES: every in-range triple survives exactly, out-of-range
    # postings are dead (0, 0) slots or rode along exactly in a
    # straddling/flat group
    term_rep = np.repeat(np.arange(len(z["df"])), z["df"])

    def triples(d):
        m = (d["pair_doc"] >= lo) & (d["pair_doc"] < hi)
        t = np.stack([term_rep[m], d["pair_doc"][m],
                      d["pair_tf"][m]], axis=1)
        return t[np.lexsort(t.T[::-1])]

    assert np.array_equal(triples(lean), triples(full))
    out = (lean["pair_doc"] < lo) | (lean["pair_doc"] >= hi)
    dead = out & (lean["pair_tf"] == 0) & (lean["pair_doc"] == 0)
    ride = out & ~dead
    # ride-along postings carry their exact raw values (check against
    # the full decode's triples for the same docs)
    fmap = {(int(a), int(b)): int(c) for a, b, c in zip(
        term_rep, full["pair_doc"], full["pair_tf"])}
    for t_, d_, v_ in zip(term_rep[ride], lean["pair_doc"][ride],
                          lean["pair_tf"][ride]):
        assert fmap[(int(t_), int(d_))] == int(v_)
    assert np.array_equal(lean["df"], full["df"])


# ---------------------------------------------------------------------------
# migrate: roundtrip, idempotence, crash, corruption
# ---------------------------------------------------------------------------


def part_bytes(idx, meta):
    return {s: open(fmt.part_path(idx, s), "rb").read()
            for s in range(meta.num_shards)}


def test_migrate_compress_roundtrip_byte_identical(tmp_path):
    idx = str(tmp_path / "idx")
    build(write_corpus(tmp_path / "c.trec"), idx)
    meta = fmt.IndexMetadata.load(idx)
    raw = part_bytes(idx, meta)
    raw_results = results(idx)

    r = migrate_index(idx, to_version=fmt.COMPRESSED_FORMAT_VERSION)
    assert r["ok"] and r["migrated"] == meta.num_shards
    meta2 = fmt.IndexMetadata.load(idx)
    assert meta2.format_version == fmt.COMPRESSED_FORMAT_VERSION
    assert meta2.compressed and not meta2.tf_lossy
    assert verify_index(idx)["ok"]
    assert_bit_identical(results(idx), raw_results, "compressed serve")

    # idempotent: a second run rewrites nothing
    r2 = migrate_index(idx, to_version=fmt.COMPRESSED_FORMAT_VERSION)
    assert r2["migrated"] == 0 and r2["skipped"] == meta.num_shards

    # rollback restores the raw parts BYTE-identically
    r3 = migrate_index(idx, to_version=fmt.ARENA_FORMAT_VERSION)
    assert r3["ok"]
    meta3 = fmt.IndexMetadata.load(idx)
    assert meta3.format_version == fmt.ARENA_FORMAT_VERSION
    assert part_bytes(idx, meta3) == raw
    assert verify_index(idx)["ok"]


def test_migrate_sigkill_mid_compress_leaves_verifiable_dir(
        tmp_path, monkeypatch):
    """A crash after shard 0's twin swap leaves a MIXED dir that still
    loads, verifies, and serves; the doctor says 'mixed'; a re-run
    completes the migration (skipping the finished shard)."""
    idx = str(tmp_path / "idx")
    build(write_corpus(tmp_path / "c.trec"), idx)
    raw_results = results(idx)

    real = fmt.save_shard
    calls = {"n": 0}

    def dying_save(*a, **kw):
        out = real(*a, **kw)
        calls["n"] += 1
        if calls["n"] == 1:
            raise KeyboardInterrupt  # the SIGKILL stand-in: post-rename
        return out

    monkeypatch.setattr(fmt, "save_shard", dying_save)
    with pytest.raises(KeyboardInterrupt):
        migrate_index(idx, to_version=fmt.COMPRESSED_FORMAT_VERSION)
    monkeypatch.setattr(fmt, "save_shard", real)

    # metadata was never rewritten: it still says v2, checksums still
    # name the surviving raw parts; the swapped shard is a valid arena
    meta = fmt.IndexMetadata.load(idx)
    assert meta.format_version == fmt.ARENA_FORMAT_VERSION
    from tpu_ir.index.doctor import doctor_report

    rep = doctor_report(idx)
    compn = rep["compression"]
    assert compn["compressed_shards"] == 1
    assert compn["raw_shards"] == meta.num_shards - 1
    assert any("mixed shard formats" in w for w in rep["warnings"])
    assert_bit_identical(results(idx), raw_results, "mixed dir serve")

    r = migrate_index(idx, to_version=fmt.COMPRESSED_FORMAT_VERSION)
    assert r["ok"] and r["skipped"] == 1
    assert r["migrated"] == meta.num_shards - 1
    assert verify_index(idx)["ok"]
    assert_bit_identical(results(idx), raw_results, "completed migrate")


def test_corrupt_compressed_part_raises_loud_integrity_error(tmp_path):
    """Payload corruption in a compressed part surfaces as ONE
    structured IntegrityError naming the file — on verify and on the
    verified serving load (postings are DATA: no silent fallback)."""
    idx = str(tmp_path / "idx")
    build(write_corpus(tmp_path / "c.trec"), idx)
    migrate_index(idx, to_version=fmt.COMPRESSED_FORMAT_VERSION)
    path = fmt.part_path(idx, 0)
    blob = bytearray(open(path, "rb").read())
    blob[-64] ^= 0xFF  # deep in the last section's payload
    open(path, "wb").write(bytes(blob))
    with pytest.raises(faults.IntegrityError) as ei:
        verify_index(idx)
    assert os.path.basename(path) in str(ei.value)
    with pytest.raises(faults.IntegrityError):
        Scorer.load(idx, layout="sparse", verify_integrity=True)


def test_corrupt_blockmax_on_compressed_quarantines_and_recomputes(
        tmp_path):
    """Derived data keeps the quarantine-and-recompute contract on a
    compressed index: a corrupt bounds artifact is quarantined and the
    bounds are recomputed from the DECODED postings — serving results
    stay bit-identical to the raw index."""
    idx = str(tmp_path / "idx")
    build(write_corpus(tmp_path / "c.trec"), idx)
    raw_results = results(idx)
    migrate_index(idx, to_version=fmt.COMPRESSED_FORMAT_VERSION)
    bpath = os.path.join(idx, "blockmax.arena")
    blob = bytearray(open(bpath, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(bpath, "wb").write(bytes(blob))
    # no serving cache in the way: force the eager path to see the rot
    import shutil

    shutil.rmtree(os.path.join(idx, "serving-tiered"),
                  ignore_errors=True)
    got = results(idx)
    assert os.path.exists(os.path.join(idx, fmt.QUARANTINE_DIR,
                                       "blockmax.arena"))
    assert recovery_counters().snapshot()["integrity_failures"] >= 1
    assert_bit_identical(got, raw_results, "recomputed bounds")


# ---------------------------------------------------------------------------
# serving parity matrix + the quantized strip
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("scoring", ["tfidf", "bm25"])
@pytest.mark.parametrize("blockmax", ["0", "1"])
def test_serving_parity_compress_on_off(tmp_path, monkeypatch, scoring,
                                        blockmax):
    """The dual-path contract: TPU_IR_COMPRESS on/off serves the same
    docids and the same float32 score BITS, with block-max pruning on
    and off (pruning composes with decode: blocks below tau are
    skipped BEFORE decode)."""
    corpus = write_corpus(tmp_path / "c.trec")
    raw_idx = str(tmp_path / "raw")
    cmp_idx = str(tmp_path / "cmp")
    build(corpus, raw_idx)
    monkeypatch.setenv("TPU_IR_COMPRESS", "1")
    build(corpus, cmp_idx)
    assert fmt.IndexMetadata.load(cmp_idx).compressed
    monkeypatch.setenv("TPU_IR_BLOCKMAX", blockmax)
    got = results(cmp_idx, scoring=scoring)
    monkeypatch.setenv("TPU_IR_COMPRESS", "0")
    want = results(raw_idx, scoring=scoring)
    assert_bit_identical(got, want, f"{scoring}/blockmax={blockmax}")


def test_bf16_strip_engages_and_stays_bit_exact(tmp_path, monkeypatch):
    """On a compressed index the resident hot strip is bf16 (every tf
    <= 256 round-trips exactly) and the pre-weighted strip cache is
    built from the widened copy — fp32, bit-identical to raw's."""
    import jax.numpy as jnp

    corpus = write_corpus(tmp_path / "c.trec")
    cmp_idx = str(tmp_path / "cmp")
    monkeypatch.setenv("TPU_IR_COMPRESS", "1")
    build(corpus, cmp_idx)
    s = Scorer.load(cmp_idx, layout="sparse")
    assert s.hot_tfs.dtype == jnp.bfloat16
    ws = s._hot_wstrip("tfidf")
    if ws is not None:  # budget-dependent; when cached it must be f32
        assert ws.dtype == jnp.float32
    monkeypatch.delenv("TPU_IR_COMPRESS")
    raw_idx = str(tmp_path / "raw")
    build(corpus, raw_idx)
    s2 = Scorer.load(raw_idx, layout="sparse")
    assert s2.hot_tfs.dtype == jnp.float32


def test_doc_range_worker_lean_load_bit_parity(tmp_path, monkeypatch):
    """A doc-range worker on a compressed index decodes only blocks
    intersecting its range (decode.bytes shrinks) and scores in-range
    docs bit-identically to the unrestricted scorer."""
    corpus = write_corpus(tmp_path / "c.trec", n_docs=400)
    idx = str(tmp_path / "idx")
    monkeypatch.setenv("TPU_IR_COMPRESS", "1")
    monkeypatch.setenv("TPU_IR_BLOCKMAX_WIDTH", "64")
    build(corpus, idx)
    from tpu_ir.obs import get_registry

    reg = get_registry()
    before_dec = reg.get("decode.bytes")
    worker = Scorer.load(idx, layout="sparse", doc_range=(1, 80))
    touched = reg.get("decode.bytes") - before_dec
    assert reg.get("decode.blocks_skipped") > 0
    full = Scorer.load(idx, layout="sparse")
    for q in QUERIES:
        w = {r[0]: r[1] for r in worker.search(q, k=50)}
        f = {r[0]: r[1] for r in full.search(q, k=400)}
        for docno, score in w.items():
            assert np.float32(score).tobytes() == \
                np.float32(f[docno]).tobytes(), (q, docno)
    # the lean load really read less payload than the later full one
    assert reg.get("decode.bytes_skipped") > 0
    assert touched < reg.get("decode.bytes") - before_dec


# ---------------------------------------------------------------------------
# serving cache key v7: the revalidation blind spot (satellite 2)
# ---------------------------------------------------------------------------


def test_cache_misses_after_mtime_preserving_compress(tmp_path):
    """A serving cache written on the RAW index must MISS after
    `migrate-index --compress`, even when the migration preserves the
    old part mtimes — the v6 blind spot this PR closes by folding the
    section-dtype signature (and format/tf metadata) into the key."""
    from tpu_ir.search.layout import load_serving_cache

    idx = str(tmp_path / "idx")
    build(write_corpus(tmp_path / "c.trec"), idx)
    meta = fmt.IndexMetadata.load(idx)
    Scorer.load(idx, layout="sparse")  # writes serving-tiered/
    assert load_serving_cache(idx, meta=meta) is not None
    old_stats = {s: os.stat(fmt.part_path(idx, s))
                 for s in range(meta.num_shards)}
    raw_results = results(idx)

    migrate_index(idx, to_version=fmt.COMPRESSED_FORMAT_VERSION)
    for s in range(meta.num_shards):
        st = old_stats[s]
        os.utime(fmt.part_path(idx, s),
                 ns=(st.st_atime_ns, st.st_mtime_ns))
    meta2 = fmt.IndexMetadata.load(idx)
    assert load_serving_cache(idx, meta=meta2) is None
    assert_bit_identical(results(idx), raw_results,
                         "post-migrate serve")


def test_cache_key_carries_section_dtype_signature(tmp_path):
    """Unit pin for the v7 key: identical injected part digests still
    yield DIFFERENT keys when the parts' section dtypes differ (int8
    vs bf16 tf encodings) — the stat fast path rebuilds the key from
    recorded digests, so only a fresh-from-disk field can catch an
    interpretation flip."""
    from tpu_ir.search.layout import _serving_cache_key

    idx = str(tmp_path / "idx")
    build(write_corpus(tmp_path / "c.trec"), idx)
    meta = fmt.IndexMetadata.load(idx)
    migrate_index(idx, to_version=fmt.COMPRESSED_FORMAT_VERSION,
                  tf_dtype="int8")
    crcs = {os.path.basename(fmt.part_path(idx, s)): "crc32:00000000"
            for s in range(meta.num_shards)}
    m1 = fmt.IndexMetadata.load(idx)
    k1 = _serving_cache_key(idx, m1, 1, 1, 1, part_crcs=crcs)
    migrate_index(idx, to_version=fmt.ARENA_FORMAT_VERSION)
    migrate_index(idx, to_version=fmt.COMPRESSED_FORMAT_VERSION,
                  tf_dtype="bf16")
    m2 = fmt.IndexMetadata.load(idx)
    k2 = _serving_cache_key(idx, m2, 1, 1, 1, part_crcs=crcs)
    # digests injected equal: the CRC column alone cannot distinguish
    # the two encodings on the stat fast path (it is rebuilt from the
    # manifest's recorded digests) — only the fresh-from-disk fields can
    assert [f[2] for f in k1["part_files"]] == \
        [f[2] for f in k2["part_files"]]
    assert k1["section_dtypes"] != k2["section_dtypes"]
    assert k1 != k2


# ---------------------------------------------------------------------------
# CLI + doctor + verify loudness
# ---------------------------------------------------------------------------


def test_cli_migrate_compress_doctor_decompress(tmp_path, capsys):
    idx = str(tmp_path / "idx")
    build(write_corpus(tmp_path / "c.trec"), idx)

    assert main(["migrate-index", idx, "--compress"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["ok"] and out["format_version"] == \
        fmt.COMPRESSED_FORMAT_VERSION
    assert out["tf_dtype"] in ("int8", "bf16")

    assert main(["doctor", idx]) == 0
    rep = json.loads(capsys.readouterr().out.strip())
    compn = rep["compression"]
    assert compn["compressed_shards"] == rep["metadata"]["num_shards"]
    assert compn["ratio"] is not None
    assert compn["bytes_per_doc"] > 0
    assert "projected_worker_hbm_bytes" in compn

    assert main(["verify", idx]) == 0
    v = json.loads(capsys.readouterr().out.strip())
    assert v["ok"] and v["compressed"] and not v["tf_lossy"]

    assert main(["migrate-index", idx, "--decompress"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["ok"] and out["format_version"] == \
        fmt.ARENA_FORMAT_VERSION

    # --compress and --decompress are mutually exclusive: exit 2
    assert main(["migrate-index", idx, "--compress",
                 "--decompress"]) == 2
    capsys.readouterr()


def test_verify_loud_on_lossy(tmp_path, monkeypatch):
    """A hand-built lossy index verifies (structure intact) but the
    report carries the lossy warning; tf-mass conservation is skipped,
    not silently passed."""
    idx = str(tmp_path / "idx")
    build(write_corpus(tmp_path / "c.trec"), idx)
    migrate_index(idx, to_version=fmt.COMPRESSED_FORMAT_VERSION)
    meta = fmt.IndexMetadata.load(idx)
    meta.tf_lossy = True  # the stamp a lossy int8 migration leaves
    meta.save_with_checksums(idx, compress=False)
    v = verify_index(idx)
    assert v["ok"] and v["tf_lossy"]
    assert "lossy" in v["tf_lossy_warning"]
    from tpu_ir.index.doctor import doctor_report

    rep = doctor_report(idx)
    assert any("LOSSY" in w for w in rep["warnings"])
