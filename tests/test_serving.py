"""Overload-serving acceptance suite: admission control, the degradation
ladder, the circuit breaker, and the concurrent chaos soak.

The contract (ISSUE 2): under concurrency + injected faults, every
response is either correct-full (bit-identical to a serial reference),
tagged-degraded (level / degraded flag explains the divergence), or a
structured Overloaded rejection — and nothing deadlocks, nothing is
silently wrong, and shed + served always equals submitted."""

import json
import threading
import time

import numpy as np
import pytest

import tpu_ir.faults as faults
from tpu_ir.index.streaming import build_index_streaming
from tpu_ir.search import Scorer
from tpu_ir.serving import (
    LEVEL_FULL,
    LEVEL_NO_RERANK,
    LEVEL_SHED,
    AdmissionController,
    CircuitBreaker,
    DegradationLadder,
    Overloaded,
    ServingConfig,
    ServingFrontend,
    run_soak,
)
from tpu_ir.utils.report import recovery_counters, serving_counters

WORDS = ("salmon fishing river bears honey quick brown fox lazy dog "
         "market investor asset bond stock season rain forest".split())


@pytest.fixture(autouse=True)
def _clean_state():
    faults.clear()
    recovery_counters().reset()
    serving_counters().reset()
    yield
    faults.clear()
    faults.drain_abandoned(timeout_s=10.0)
    recovery_counters().reset()
    serving_counters().reset()


def write_corpus(path, n_docs=120):
    body = []
    for i in range(n_docs):
        text = " ".join(WORDS[(i + j) % len(WORDS)]
                        for j in range(3 + (i % 7)))
        body.append(f"<DOC>\n<DOCNO> D-{i:04d} </DOCNO>\n<TEXT>\n"
                    f"{text}\n</TEXT>\n</DOC>\n")
    path.write_text("".join(body))
    return str(path)


@pytest.fixture(scope="module")
def index_dir(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("serving")
    corpus = write_corpus(tmp / "corpus.trec")
    out = str(tmp / "idx")
    build_index_streaming([corpus], out, k=1, num_shards=3,
                          batch_docs=40, chargram_ks=[])
    return out


@pytest.fixture(scope="module")
def scorer(index_dir):
    s = Scorer.load(index_dir, layout="sparse")
    # warm the compile caches so per-request deadlines in these tests
    # measure serving, not XLA compilation
    s.search_batch(["salmon fishing"], k=5, scoring="bm25")
    s.search_batch(["salmon fishing"], k=5, scoring="tfidf")
    s.search_batch(["salmon fishing"], k=5, rerank=25)
    return s


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


def test_admission_sheds_past_queue_capacity():
    adm = AdmissionController(max_concurrency=1, max_queue=1)
    release = threading.Event()
    holding = threading.Event()
    waiting = threading.Event()

    def holder():
        with adm.admit():
            holding.set()
            release.wait(10)

    def waiter():
        waiting.set()
        with adm.admit(queue_timeout_s=10):
            pass

    threads = [threading.Thread(target=holder, daemon=True)]
    threads[0].start()
    assert holding.wait(5)
    threads.append(threading.Thread(target=waiter, daemon=True))
    threads[1].start()
    assert waiting.wait(5)
    deadline = time.monotonic() + 5
    while adm.queue_depth() < 1 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert adm.queue_depth() == 1 and adm.pressure() == 1.0
    # queue full: the third request sheds IMMEDIATELY with structure
    t0 = time.perf_counter()
    with pytest.raises(Overloaded) as ei:
        with adm.admit():
            pass
    assert time.perf_counter() - t0 < 0.5, "shed was not immediate"
    assert ei.value.reason == "queue_full"
    assert ei.value.queue_depth == 1
    release.set()
    for t in threads:
        t.join(10)
    assert adm.queue_depth() == 0 and adm.pressure() == 0.0


def test_admission_zero_queue_executes_without_queuing():
    """max_queue=0 means 'execute, never queue' — an idle controller
    must still admit up to max_concurrency, and only a request that
    would have to WAIT is shed."""
    adm = AdmissionController(max_concurrency=2, max_queue=0)
    with adm.admit():
        assert adm.queue_depth() == 0     # executing != waiting
        with adm.admit():
            with pytest.raises(Overloaded) as ei:
                with adm.admit():
                    pass
            assert ei.value.reason == "queue_full"
    with adm.admit():                     # slots free again
        pass


def test_admission_queue_timeout_sheds():
    adm = AdmissionController(max_concurrency=1, max_queue=4)
    release = threading.Event()
    holding = threading.Event()

    def holder():
        with adm.admit():
            holding.set()
            release.wait(10)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert holding.wait(5)
    with pytest.raises(Overloaded) as ei:
        with adm.admit(queue_timeout_s=0.05):
            pass
    assert ei.value.reason == "queue_timeout"
    release.set()
    t.join(10)


# ---------------------------------------------------------------------------
# circuit breaker state machine
# ---------------------------------------------------------------------------


def test_breaker_state_machine_with_probes():
    clock = {"t": 0.0}
    br = CircuitBreaker(failure_threshold=3, cooldown_s=1.0,
                        clock=lambda: clock["t"])
    assert br.state == "closed"
    for _ in range(2):
        assert br.allow_device() == (True, False)
        assert not br.record_failure()
    assert br.state == "closed"          # under threshold
    assert br.allow_device() == (True, False)
    assert br.record_failure()           # third consecutive: OPENS (True)
    assert br.state == "open"
    assert br.allow_device() == (False, False)  # cooldown not elapsed
    clock["t"] = 0.5
    assert br.allow_device() == (False, False)
    clock["t"] = 1.5                     # cooldown elapsed: ONE probe
    assert br.allow_device() == (True, True)
    assert br.state == "half_open"
    assert br.allow_device() == (False, False)  # probe slot is exclusive
    assert br.record_failure(is_probe=True)  # probe failed: RE-opens
    assert br.state == "open"            # (counted — operators see flap)
    assert br.allow_device() == (False, False)
    clock["t"] = 3.0
    assert br.allow_device() == (True, True)    # second probe
    br.record_success(is_probe=True)     # device is back
    assert br.state == "closed"
    assert br.allow_device() == (True, False)
    snap = br.snapshot()
    assert snap["opened_count"] == 2 and snap["probe_count"] == 2


def test_breaker_stale_verdicts_cannot_move_the_state():
    """Verdicts are attributed by the is_probe token allow_device handed
    the request, never by re-reading shared state: a request admitted
    BEFORE the breaker opened must not close it with a late success,
    and its late failure must not consume (or delay) the probe slot."""
    clock = {"t": 0.0}
    br = CircuitBreaker(failure_threshold=2, cooldown_s=1.0,
                        clock=lambda: clock["t"])
    assert br.allow_device() == (True, False)   # request A, slow
    for _ in range(2):                   # B, C fail: breaker opens
        br.allow_device()
        br.record_failure()
    assert br.state == "open"
    clock["t"] = 1.5
    assert br.allow_device() == (True, True)    # probe P in flight
    br.record_success(is_probe=False)    # A's STALE success arrives
    assert br.state == "half_open", \
        "a stale pre-open success must not close the breaker"
    br.record_failure(is_probe=False)    # another stale failure
    assert br.state == "half_open", \
        "a stale failure must not consume the probe slot"
    br.record_success(is_probe=True)     # P's real verdict
    assert br.state == "closed"


def test_breaker_abort_releases_probe_slot():
    """A probe request dying WITHOUT a device verdict (bad query, program
    bug) must release the exclusive probe slot — otherwise the breaker
    wedges half-open and all traffic serves the fallback forever."""
    clock = {"t": 0.0}
    br = CircuitBreaker(failure_threshold=1, cooldown_s=1.0,
                        clock=lambda: clock["t"])
    br.allow_device()
    br.record_failure()                  # opens
    clock["t"] = 1.5
    assert br.allow_device() == (True, True)    # the probe
    br.abort(is_probe=True)              # probe died verdictless
    assert br.state == "open"
    clock["t"] = 3.0
    assert br.allow_device() == (True, True), \
        "a later probe must still be possible after an aborted one"
    # abort of a non-probe request is a no-op
    br.record_success(is_probe=True)
    br.abort(is_probe=False)
    assert br.state == "closed" and br.allow_device() == (True, False)


def test_frontend_exception_releases_probe_and_surfaces(scorer, monkeypatch):
    """A request error during the half-open probe must neither be
    swallowed nor wedge the breaker (the probe slot is released)."""
    from tpu_ir.search.scorer import Scorer as ScorerCls

    cfg = ServingConfig(deadline_s=1.0, breaker_threshold=1,
                        breaker_cooldown_s=0.0, fail_threshold=1000)
    fe = ServingFrontend(scorer, cfg)
    faults.install(faults.parse_plan("score.device_loss:once@1"))
    try:
        fe.search("salmon fishing", k=5)          # opens the breaker
        assert fe.breaker.state == "open"
    finally:
        faults.clear()

    def boom(self, *a, **kw):
        raise RuntimeError("not a device verdict")

    with monkeypatch.context() as m:
        m.setattr(ScorerCls, "search_batch", boom)
        with pytest.raises(RuntimeError):
            fe.search("salmon fishing", k=5)      # the probe, dying
    # slot released: the next request can probe for real and close
    res = fe.search("salmon fishing", k=5)
    assert not res.degraded and fe.breaker.state == "closed"


# ---------------------------------------------------------------------------
# degradation ladder
# ---------------------------------------------------------------------------


def test_ladder_steps_down_up_with_hysteresis():
    clock = {"t": 0.0}
    moves = []
    cfg = ServingConfig(fail_threshold=2, recover_successes=3,
                        step_down_pressure=0.75, step_up_pressure=0.25,
                        down_cooldown_s=1.0)
    ladder = DegradationLadder(
        ("full", "no_rerank", "shed"), cfg,
        lambda *m: moves.append(m), clock=lambda: clock["t"])
    assert ladder.level() == "full"
    ladder.observe(pressure=0.0, failed=True)
    assert ladder.level() == "full"      # one failure is not a trend
    ladder.observe(pressure=0.0, failed=True)
    assert ladder.level() == "no_rerank"  # fail_threshold reached
    # a second trigger inside the cooldown must NOT cascade to shed
    ladder.observe(pressure=1.0, failed=False)
    assert ladder.level() == "no_rerank"
    clock["t"] = 2.0
    ladder.observe(pressure=1.0, failed=False)
    assert ladder.level() == "shed"      # cooldown elapsed: steps again
    # recovery: calm observations, one level at a time, earned each time
    for _ in range(3):
        assert ladder.level() == "shed"
        ladder.observe(pressure=0.0, failed=False)
    assert ladder.level() == "no_rerank"
    ladder.observe(pressure=0.5, failed=False)   # middle zone: no credit
    for _ in range(3):
        ladder.observe(pressure=0.0, failed=False)
    assert ladder.level() == "full"
    assert [m[0] for m in moves] == ["down", "down", "up", "up"]


# ---------------------------------------------------------------------------
# frontend behavior
# ---------------------------------------------------------------------------


def test_frontend_full_level_matches_scorer(scorer):
    fe = ServingFrontend(scorer, ServingConfig(deadline_s=5.0))
    res = fe.search("salmon fishing", k=5, scoring="bm25")
    assert res.level == LEVEL_FULL and not res.degraded
    direct = scorer.search_batch(["salmon fishing"], k=5,
                                 scoring="bm25")[0]
    assert list(res) == list(direct)
    st = fe.stats()
    assert st["submitted"] == 1 and st["served_full"] == 1


def test_frontend_steps_down_and_tags_levels(scorer):
    """Repeated dispatch failures walk the ladder down; each response is
    tagged with the level that served it, and the rerank stage is
    actually dropped below full."""
    cfg = ServingConfig(deadline_s=1.0, fail_threshold=2,
                        down_cooldown_s=0.0, breaker_threshold=1000)
    fe = ServingFrontend(scorer, cfg)
    faults.install(faults.parse_plan("score.device_loss:first@4"))
    try:
        seen = []
        for _ in range(4):
            res = fe.search("salmon river", k=5, scoring="bm25",
                            rerank=25)
            seen.append((res.level, res.degraded))
    finally:
        faults.clear()
    # first two failures at full; third request served at no_rerank
    assert seen[0] == (LEVEL_FULL, True) and seen[1] == (LEVEL_FULL, True)
    assert seen[2][0] == LEVEL_NO_RERANK
    assert fe.stats()["level_step_down"] >= 1
    # ladder levels on the tiered layout include hot_only
    assert fe.ladder.levels == ("full", "no_rerank", "hot_only", "shed")


def test_frontend_shed_level_rejects_and_recovers(scorer):
    cfg = ServingConfig(deadline_s=1.0, fail_threshold=1,
                        down_cooldown_s=0.0, recover_successes=2,
                        breaker_threshold=1000)
    fe = ServingFrontend(scorer, cfg)
    faults.install(faults.parse_plan("score.device_loss:first@3"))
    try:
        for _ in range(3):   # full -> no_rerank -> hot_only -> shed
            fe.search("salmon fishing", k=3)
    finally:
        faults.clear()
    assert fe.ladder.level() == LEVEL_SHED
    with pytest.raises(Overloaded) as ei:
        fe.search("salmon fishing", k=3)
    assert ei.value.reason == "shed_level" and ei.value.level == LEVEL_SHED
    # shed observations under calm pressure earn the way back up
    for _ in range(20):
        try:
            fe.search("salmon fishing", k=3)
        except Overloaded:
            continue
    assert fe.ladder.level() == LEVEL_FULL
    st = fe.stats()
    assert st["shed_level"] >= 1
    assert st["level_step_up"] >= 3
    assert st["submitted"] == st.get("shed_level", 0) + sum(
        v for k, v in st.items()
        if isinstance(v, int) and k.startswith("served_"))


# ---------------------------------------------------------------------------
# circuit breaker saves work (the >= 10x latency criterion)
# ---------------------------------------------------------------------------


def test_breaker_open_is_10x_faster_than_deadline_per_request(scorer):
    """With the device path forced down (every dispatch hangs), the
    closed breaker pays the full deadline per request; once open, the
    frontend serves the host fallback directly — steady-state latency
    must be at least 10x below deadline-per-request."""
    deadline = 0.25
    cfg = ServingConfig(deadline_s=deadline, breaker_threshold=2,
                        breaker_cooldown_s=300.0,  # no probes mid-test
                        fail_threshold=1000)       # isolate the breaker
    fe = ServingFrontend(scorer, cfg)
    faults.install(faults.FaultPlan().add("score.hang", "always",
                                          sleep_s=1.0))
    try:
        t0 = time.perf_counter()
        r1 = fe.search("salmon fishing", k=5)
        closed_latency = time.perf_counter() - t0
        assert r1.degraded
        assert closed_latency >= deadline * 0.8, \
            "closed-state failure should pay ~the deadline"
        fe.search("stock market", k=5)            # second failure: opens
        assert fe.breaker.state == "open"

        lat = []
        for i in range(20):
            t0 = time.perf_counter()
            res = fe.search(f"salmon river {WORDS[i % len(WORDS)]}", k=5)
            lat.append(time.perf_counter() - t0)
            assert res.degraded, "breaker-open serving must stay tagged"
        steady = sum(lat) / len(lat)
    finally:
        faults.clear()
    assert fe.stats()["served_breaker_host"] == 20
    assert steady * 10 <= deadline, (
        f"open-breaker latency {steady:.4f}s not >=10x below the "
        f"{deadline}s deadline")


def test_breaker_probe_closes_on_recovery(scorer):
    cfg = ServingConfig(deadline_s=1.0, breaker_threshold=1,
                        breaker_cooldown_s=0.05, fail_threshold=1000)
    fe = ServingFrontend(scorer, cfg)
    faults.install(faults.parse_plan("score.device_loss:once@1"))
    try:
        r = fe.search("salmon fishing", k=5)
        assert r.degraded and fe.breaker.state == "open"
        time.sleep(0.08)                 # cooldown elapses; plan exhausted
        r2 = fe.search("salmon fishing", k=5)   # the half-open probe
        assert not r2.degraded and r2.level == LEVEL_FULL
        assert fe.breaker.state == "closed"
        assert fe.stats()["breaker_probes"] == 1
    finally:
        faults.clear()


# ---------------------------------------------------------------------------
# the concurrent chaos soak (fast tier-1 variant + long slow variant)
# ---------------------------------------------------------------------------


def _assert_soak_invariants(report):
    assert report["deadlocked"] == 0, "soak deadlocked"
    assert report["errors"] == 0, report["error_samples"]
    assert report["untagged_mismatches"] == 0, \
        "untagged response diverged from the serial reference"
    assert report["served"] + report["shed"] == report["submitted"]
    fe = report["frontend"]
    assert fe["submitted"] == report["submitted"]
    served_by_level = sum(v for k, v in fe.items()
                          if isinstance(v, int) and k.startswith("served_")
                          and k != "served_breaker_host")
    shed_total = sum(v for k, v in fe.items()
                     if isinstance(v, int) and k.startswith("shed_"))
    assert served_by_level == report["served"]
    assert shed_total == report["shed"]


def test_soak_fast_8x200_under_chaos(scorer):
    """The tier-1 acceptance soak: >= 8 worker threads x >= 200 mixed
    queries with hang + device-loss sites firing, done in seconds on a
    fixed seed. Zero deadlocks, zero untagged divergence, conservation
    of requests — and the chaos must actually bite (degradations
    observed), or the run proved nothing."""
    report = run_soak(
        scorer, threads=8, queries=220, seed=0,
        fault_spec=("score.hang:p=0.15:sleep=0.5,"
                    "score.device_loss:p=0.1,seed=2"),
        config=ServingConfig(max_concurrency=3, max_queue=4,
                             deadline_s=0.2, queue_timeout_s=0.15,
                             breaker_threshold=4,
                             breaker_cooldown_s=0.2),
        timeout_s=90.0, pacing_s=0.002)
    _assert_soak_invariants(report)
    assert report["submitted"] == 220 and report["threads"] == 8
    # the chaos bit: degraded responses exist and are all tagged
    assert report["degraded"] > 0
    assert report["full_bitidentical"] > 0, \
        "no healthy full response was verified against the reference"
    rec = report["recovery_delta"]
    assert (rec.get("degraded_batches", 0)
            + rec.get("forced_host_batches", 0)) == report["degraded"]


def test_soak_without_faults_serves_everything_full(scorer):
    """Control run: no fault plan, light load — everything serves at
    full level, bit-identical, nothing degraded, nothing shed."""
    report = run_soak(
        scorer, threads=4, queries=60, seed=3, fault_spec=None,
        config=ServingConfig(max_concurrency=4, max_queue=16,
                             deadline_s=5.0),
        timeout_s=60.0)
    _assert_soak_invariants(report)
    assert report["shed"] == 0 and report["degraded"] == 0
    assert report["levels"] == {"full": 60}
    assert report["full_bitidentical"] == 60


def test_soak_with_coalescing_under_chaos(scorer):
    """The PR 2 chaos soak THROUGH the continuous micro-batching
    frontend (ISSUE 9): all the original invariants must survive shared
    padded batches — shed + served == submitted, every response
    bit-identical-full / tagged / structurally rejected, zero deadlocks
    (and the module's OrderedLock arming re-verifies the scheduler's
    lock discipline on every schedule) — plus the batching-specific
    pin: degradation within one coalesced batch is UNIFORM, so no
    request is ever charged a deadline a batch-mate's slow slot burned
    (batch_mixed_degraded == 0)."""
    from tpu_ir.obs import querylog

    querylog.clear()
    report = run_soak(
        scorer, threads=8, queries=200, seed=7,
        fault_spec=("score.hang:p=0.12:sleep=0.5,"
                    "score.device_loss:p=0.08,seed=9"),
        config=ServingConfig(max_concurrency=6, max_queue=8,
                             deadline_s=0.2, queue_timeout_s=0.15,
                             breaker_threshold=4,
                             breaker_cooldown_s=0.2, coalesce=True),
        timeout_s=120.0, pacing_s=0.002)
    _assert_soak_invariants(report)
    assert report["submitted"] == 200
    assert report["degraded"] > 0, "the chaos never bit"
    batching = report["batching"]
    assert batching["batches"] > 0
    assert batching["coalesced"] + batching["solo_flush"] == \
        batching["batches"]
    assert batching["queued"] == 0 and not batching["dispatching"]
    # the per-slot attribution invariant (tag, don't drop)
    assert report["batch_mixed_degraded"] == 0


@pytest.mark.slow
def test_soak_long_sustained_chaos(scorer):
    """The long soak: sustained mixed traffic with heavier chaos and
    more workers; same invariants, plus the control plane must have
    cycled (breaker opened AND recovered via probes at least once)."""
    report = run_soak(
        scorer, threads=16, queries=3000, seed=1,
        fault_spec=("score.hang:p=0.1:sleep=0.4,"
                    "score.device_loss:p=0.08,seed=5"),
        config=ServingConfig(max_concurrency=4, max_queue=8,
                             deadline_s=0.2, breaker_threshold=4,
                             breaker_cooldown_s=0.15),
        timeout_s=480.0, pacing_s=0.004)
    _assert_soak_invariants(report)
    assert report["degraded"] > 0
    fe = report["frontend"]
    assert fe.get("breaker_opened", 0) >= 1
    assert fe.get("breaker_probes", 0) >= 1


# ---------------------------------------------------------------------------
# stats + serve-bench CLI surfaces
# ---------------------------------------------------------------------------


def test_stats_cli_output_shape(capsys):
    from tpu_ir.cli import main

    recovery_counters().incr("degraded_batches", 2)
    serving_counters().incr("submitted", 5)
    faults.install(faults.parse_plan("score.hang:once@1"))
    faults.active().should_fire("score.hang")
    rc = main(["stats"])
    faults.clear()
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    # the ISSUE 3 contract: a strict SUPERSET of the PR 2 shape — the
    # three counter sections keep their exact form, histograms ride along
    assert set(out) >= {"recovery", "serving", "fault_injection",
                        "histograms"}
    for section in ("recovery", "serving", "fault_injection"):
        assert all(isinstance(k, str) and isinstance(v, int)
                   for k, v in out[section].items())
    assert out["recovery"]["degraded_batches"] == 2
    assert out["serving"]["submitted"] == 5
    assert out["fault_injection"] == {"score.hang": 1}
    # fault fires ALSO land in the unified registry's fault.* namespace
    assert out["recovery"] != out["histograms"]  # distinct sections
    assert "dispatch" in out["histograms"]


def test_serve_bench_cli_runs_and_reports(index_dir, capsys):
    from tpu_ir.cli import main

    rc = main(["serve-bench", index_dir, "--backend", "cpu",
               "--layout", "sparse", "--queries", "40", "--threads", "4",
               "--chaos", "--deadline", "0.2"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["submitted"] == 40
    assert out["served"] + out["shed"] == 40
    assert out["deadlocked"] == 0 and out["untagged_mismatches"] == 0
    # the per-stage latency breakdown (ISSUE 3 acceptance): p50/p95/p99
    # for every serving stage, always present in the serve-bench JSON
    for stage in ("admission_wait", "dispatch", "kernel", "fallback"):
        assert {"count", "p50_ms", "p95_ms", "p99_ms"} <= \
            set(out["latency"][stage])
    assert out["latency"]["dispatch"]["count"] > 0


def test_serve_bench_honors_env_var_fault_plan(index_dir, capsys,
                                               monkeypatch):
    """TPU_IR_FAULTS (the documented env twin of --faults) must drive
    serve-bench's chaos phase — regression: lifting the plan off with
    clear() used to re-arm the env var and crash run_soak's guard."""
    from tpu_ir.cli import main

    spec = "score.device_loss:p=0.3,seed=4"
    monkeypatch.setenv("TPU_IR_FAULTS", spec)
    faults.clear()   # let active() lazily pick the env var up
    rc = main(["serve-bench", index_dir, "--backend", "cpu",
               "--layout", "sparse", "--queries", "20", "--threads", "2"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["fault_spec"] == spec
    assert out["degraded"] > 0, "the env plan never fired"
