"""Compat-oracle tests: the quirk-exact reference engine vs the TPU engine in
compat mode — documents where they match and where the engine deviates."""

import math

import pytest

from tpu_ir.compat import DOC_COUNTER_TERM, CompatIndex
from tpu_ir.index import build_index
from tpu_ir.search import Scorer

DOCS = {
    "AP-1": "gold silver gold copper",
    "AP-2": "silver iron copper tin gold",
    "AP-3": "tin zinc lead iron",
    "AP-4": "gold gold gold mercury",
    "AP-5": "platinum mercury zinc silver",
}


@pytest.fixture(scope="module")
def engines(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("compat")
    corpus = tmp / "c.trec"
    corpus.write_text("".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in DOCS.items()))
    idx = str(tmp / "idx")
    build_index([str(corpus)], idx, compute_chargrams=False, num_shards=2)
    return CompatIndex(DOCS), Scorer.load(idx, compat_int_idf=True)


def test_sentinel_doc_counter(engines):
    oracle, _ = engines
    # the " " sentinel term's df is the corpus size (reference N channel)
    assert oracle.df(DOC_COUNTER_TERM) == len(DOCS)


def test_word_cap_guard(engines):
    oracle, _ = engines
    assert oracle.rank("gold silver copper") is None  # 3 words rejected
    assert oracle.rank("") is None
    assert oracle.rank("gold") is not None
    # the guard counts RAW whitespace words (term.split("\\s+"),
    # IntDocVectorsForwardIndex.java:292,297), not analyzed tokens: "the of"
    # analyzes to zero tokens but is 2 raw words -> allowed (empty result),
    # while "gold, silver. copper!" is 3 raw words -> rejected
    assert oracle.rank("the of") == []
    assert oracle.rank("gold, silver. copper!") is None


def test_int_division_idf_matches_engine(engines):
    oracle, scorer = engines
    for q in ["gold", "silver", "zinc mercury", "iron tin"]:
        want = oracle.rank(q)
        got = scorer.search(q)
        # engine drops zero-score docs; oracle keeps them — compare the
        # positive-score prefix
        want_pos = [(d, s) for d, s in want if s > 0]
        got_d = dict(got)
        assert set(got_d) == {d for d, _ in want_pos}, q
        for d, s in want_pos:
            assert got_d[d] == pytest.approx(s, rel=1e-4), (q, d)


def test_idf_zero_when_df_equals_n():
    docs = {f"D-{i}": "common word here" for i in range(4)}
    oracle = CompatIndex(docs)
    ranked = oracle.rank("common")
    # int division: N//df = 1 -> log10(1) = 0; reference still lists docs
    assert ranked is not None and len(ranked) == 4
    assert all(s == 0.0 for _, s in ranked)


def test_ceil_comparator_tie_behavior():
    """DocScore.compareTo is (int) ceil(other - this): a doc scoring up
    to 1.0 HIGHER than an earlier-inserted doc compares 'equal' in the
    direction the stable sort asks, so it never displaces it — the
    documented reference quirk. (The old version of this test used a
    corpus where every score was 0.0, making its disjunctive assert
    vacuous — review r5.)"""
    oracle = CompatIndex({
        "D-0": "cherry",    # filler: keeps idf positive (N=4)
        "D-1": "apple",     # 0.301, inserted 1st (apple postings)
        "D-4": "apple",     # 0.301, inserted 2nd
        "D-2": "banana",    # 0.602, inserted LAST (banana postings)
    })
    ranked = oracle.rank("apple banana")
    assert ranked is not None
    assert [d for d, _ in ranked] == ["D-1", "D-4", "D-2"]
    scores = dict(ranked)
    # the quirk is discriminating: D-2 scores strictly highest yet ranks
    # last, where an exact-score sort would put it first
    assert scores["D-2"] == max(scores.values())
    assert scores["D-2"] - scores["D-1"] < 1.0
