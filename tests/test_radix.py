"""Radix-partitioned streaming build (ISSUE 11).

The contract under test: partitioning the pass-1 pair stream into radix
buckets — turning pass 2 from a global per-batch combine into
embarrassingly-parallel per-bucket local device reduces — changes WHERE
the work happens and NOTHING about the artifacts. Every build path
(legacy streaming, radix at any bucket count, radix over an SPMD mesh,
the multiprocess tokenizer) must produce byte-identical files, and every
crash/corruption recovery scope must stay as small as the layout allows:

- fuzz pins: one-shot == legacy streaming == radix(B=1/4/16) == SPMD
  radix, bit for bit (metadata checksums included);
- resume: mid-pass-1 and mid-pass-2 deaths resume without re-tokenizing
  and converge on identical bytes; a radix-config change can never
  resume over mismatched spills (signature);
- corruption: a corrupt pass-2 bucket spill recomputes ONLY that bucket;
  a corrupt pass-1 rpairs spill discards pass 1 (it cannot be rebuilt
  without re-tokenizing);
- tokenizer pool: TPU_IR_TOKENIZE_PROCS=1 vs N yield byte-identical
  spills over multi-file corpora with documents straddling chunk
  boundaries, and pool workers inherit the fault plan deterministically;
- bucket-segmented parts (TPU_IR_RADIX_PARTS): verify/inspect/
  migrate-index/Scorer accept the layout, results match the canonical
  scorer exactly.
"""

import filecmp
import json
import os

import numpy as np
import pytest

import tpu_ir.index.streaming as streaming
from tpu_ir import faults
from tpu_ir.index import build_index
from tpu_ir.index import format as fmt
from tpu_ir.index.streaming import build_index_streaming
from tpu_ir.index.verify import verify_index
from tpu_ir.search import Scorer

WORDS = ("salmon fishing river bears honey quick brown fox lazy dog "
         "market investor asset bond stock season rain forest".split())

BUILD_KW = dict(k=1, num_shards=3, batch_docs=25, chargram_ks=[2])


def write_corpus(path, n_docs=120, skew=0, prefix="D"):
    body = []
    for i in range(n_docs):
        text = " ".join(WORDS[(i + j + skew) % len(WORDS)]
                        for j in range(3 + (i % 7)))
        body.append(f"<DOC>\n<DOCNO> {prefix}-{i:04d} </DOCNO>\n<TEXT>\n"
                    f"{text}\n</TEXT>\n</DOC>\n")
    path.write_text("".join(body))
    return str(path)


def artifact_names(d):
    return sorted(
        n for n in os.listdir(d)
        if not n.startswith(".") and n != fmt.JOBS_DIR
        and not n.startswith("serving-"))


def assert_identical(got_dir, want_dir):
    names = artifact_names(want_dir)
    assert artifact_names(got_dir) == names
    for n in names:
        assert filecmp.cmp(os.path.join(want_dir, n),
                           os.path.join(got_dir, n), shallow=False), n


_REAL_TOKENIZER = streaming.make_chunked_tokenizer


def small_chunks(monkeypatch):
    """Tiny read chunks so the corpus spans several spill batches."""
    monkeypatch.setattr(
        streaming, "make_chunked_tokenizer",
        lambda paths, k=1, **kw: _REAL_TOKENIZER(
            paths, k=k, chunk_bytes=400,
            **{k2: v for k2, v in kw.items() if k2 != "chunk_bytes"}))


def forbid_tokenizer(monkeypatch):
    def boom(*a, **kw):
        raise AssertionError("resume must not re-tokenize the corpus")
    monkeypatch.setattr(streaming, "make_chunked_tokenizer", boom)


@pytest.fixture(scope="module")
def ref(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("radix")
    corpus = write_corpus(tmp / "corpus.trec")
    legacy_dir = str(tmp / "legacy")
    build_index_streaming([corpus], legacy_dir, **BUILD_KW)
    oneshot_dir = str(tmp / "oneshot")
    build_index([corpus], oneshot_dir, k=1, num_shards=3,
                chargram_ks=[2])
    return corpus, legacy_dir, oneshot_dir


# ---------------------------------------------------------------------------
# fuzz pins: bit-identical artifacts across every build path
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("buckets", [1, 4, 16])
def test_radix_bit_identical_to_legacy_and_oneshot(tmp_path, ref, buckets):
    corpus, legacy_dir, oneshot_dir = ref
    out = str(tmp_path / "idx")
    build_index_streaming([corpus], out, radix_buckets=buckets, **BUILD_KW)
    assert_identical(out, legacy_dir)
    # metadata checksums (the digests pinning every artifact's BYTES)
    # equal the one-shot builder's — the acceptance criterion verbatim
    assert (fmt.IndexMetadata.load(out).checksums
            == fmt.IndexMetadata.load(oneshot_dir).checksums)
    r = verify_index(out)
    assert r["ok"] and r["bucket_segmented_shards"] == 0


def test_radix_spmd_bit_identical(tmp_path, ref):
    """Buckets partitioned across mesh devices (no collective — each
    device reduces its own buckets locally with the same program the
    single-device path runs) must not move a single byte."""
    corpus, _, _ = ref
    kw = dict(k=1, batch_docs=25, chargram_ks=[2], radix_buckets=6)
    sd = str(tmp_path / "sd")
    spmd = str(tmp_path / "spmd")
    build_index_streaming([corpus], sd, num_shards=4, **kw)
    build_index_streaming([corpus], spmd, spmd_devices=4, **kw)
    assert_identical(spmd, sd)
    assert verify_index(spmd)["ok"]


def test_radix_multifile_and_batch_fuzz(tmp_path, ref):
    """Sweep (files, batch_docs, buckets) combinations — the bucket
    partition must be invariant to how the corpus arrives."""
    corpus, _, _ = ref
    c2 = write_corpus(tmp_path / "extra.trec", n_docs=37, skew=5,
                      prefix="E")
    want = str(tmp_path / "want")
    build_index_streaming([corpus, c2], want, **BUILD_KW)
    for i, (batch, buckets) in enumerate([(25, 4), (60, 16), (300, 3)]):
        out = str(tmp_path / f"got{i}")
        build_index_streaming(
            [corpus, c2], out, k=1, num_shards=3, chargram_ks=[2],
            batch_docs=batch, radix_buckets=buckets)
        assert_identical(out, want)


# ---------------------------------------------------------------------------
# resume: mid-pass deaths, bucket-scoped recovery, signature pinning
# ---------------------------------------------------------------------------


def test_radix_resume_after_pass1_crash(tmp_path, monkeypatch, ref):
    corpus, legacy_dir, _ = ref
    out = str(tmp_path / "idx")
    small_chunks(monkeypatch)
    faults.install(faults.parse_plan("crash.pass1:once@2"))
    try:
        with pytest.raises(faults.InjectedCrash):
            build_index_streaming([corpus], out, radix_buckets=4,
                                  **BUILD_KW)
    finally:
        faults.clear()
    # at least one batch's bucketed spills landed before the death
    spill = os.path.join(out, "_spill")
    assert [n for n in os.listdir(spill) if n.startswith("rpairs-")]
    build_index_streaming([corpus], out, radix_buckets=4, **BUILD_KW)
    assert_identical(out, legacy_dir)


def test_radix_resume_after_pass2_crash_skips_done_buckets(
        tmp_path, monkeypatch, ref):
    corpus, legacy_dir, _ = ref
    out = str(tmp_path / "idx")
    buckets = 6
    small_chunks(monkeypatch)
    faults.install(faults.parse_plan("crash.pass2:once@3"))
    try:
        with pytest.raises(faults.InjectedCrash):
            build_index_streaming([corpus], out, radix_buckets=buckets,
                                  **BUILD_KW)
    finally:
        faults.clear()

    # restart: the tokenizer must NOT run, and only the buckets without
    # complete pass-2 spills reduce again
    forbid_tokenizer(monkeypatch)
    calls = {"n": 0}
    real = streaming.build_postings_packed_jit
    monkeypatch.setattr(
        streaming, "build_postings_packed_jit",
        lambda *a, **kw: (calls.__setitem__("n", calls["n"] + 1),
                          real(*a, **kw))[1])
    build_index_streaming([corpus], out, radix_buckets=buckets,
                          **BUILD_KW)
    assert 1 <= calls["n"] < buckets
    assert_identical(out, legacy_dir)
    assert verify_index(out)["ok"]


def test_corrupt_bucket_pair_spill_recomputes_only_that_bucket(
        tmp_path, monkeypatch, ref):
    """A truncated/rotted PASS-2 bucket spill quarantines only its
    bucket: the restart deletes that bucket's per-shard spills and
    reduces it again — one device dispatch, not a pass-2 rerun."""
    corpus, legacy_dir, _ = ref
    out = str(tmp_path / "idx")
    buckets = 5
    faults.install(faults.parse_plan("crash.pass3:once@1"))
    try:
        with pytest.raises(faults.InjectedCrash):
            build_index_streaming([corpus], out, radix_buckets=buckets,
                                  **BUILD_KW)
    finally:
        faults.clear()
    victim = os.path.join(out, "_spill", "pairs-001-00002.npz")
    with open(victim, "r+b") as f:
        f.seek(os.path.getsize(victim) // 2)
        b = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([b[0] ^ 0xFF]))

    forbid_tokenizer(monkeypatch)
    calls = {"n": 0}
    real = streaming.build_postings_packed_jit
    monkeypatch.setattr(
        streaming, "build_postings_packed_jit",
        lambda *a, **kw: (calls.__setitem__("n", calls["n"] + 1),
                          real(*a, **kw))[1])
    build_index_streaming([corpus], out, radix_buckets=buckets,
                          **BUILD_KW)
    assert calls["n"] == 1  # bucket 2 and nothing else
    assert_identical(out, legacy_dir)


def test_corrupt_rpairs_spill_discards_pass1(tmp_path, monkeypatch, ref):
    """A rotted PASS-1 bucketed spill cannot be rebuilt without
    re-tokenizing: the manifest CRC check discards the whole pass-1
    state and the restart tokenizes again, converging on identical
    artifacts."""
    corpus, legacy_dir, _ = ref
    out = str(tmp_path / "idx")
    small_chunks(monkeypatch)
    faults.install(faults.parse_plan("crash.pass2:once@1"))
    try:
        with pytest.raises(faults.InjectedCrash):
            build_index_streaming([corpus], out, radix_buckets=4,
                                  **BUILD_KW)
    finally:
        faults.clear()
    victim = os.path.join(out, "_spill",
                          streaming.radix_spill_name(2, 1))
    with open(victim, "r+b") as f:
        f.seek(os.path.getsize(victim) // 2)
        b = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([b[0] ^ 0xFF]))

    tokenized = {"n": 0}
    def counting(*a, **kw):
        tokenized["n"] += 1
        return _REAL_TOKENIZER(*a, **kw)
    monkeypatch.setattr(streaming, "make_chunked_tokenizer", counting)
    from tpu_ir.utils.report import recovery_counters

    before = recovery_counters().get("spill_integrity_discards")
    build_index_streaming([corpus], out, radix_buckets=4, **BUILD_KW)
    assert tokenized["n"] == 1
    assert recovery_counters().get(
        "spill_integrity_discards") == before + 1
    assert_identical(out, legacy_dir)


def test_radix_config_change_never_resumes(tmp_path, monkeypatch, ref):
    """Spills partitioned at B=4 must not resume a B=8 build (or a
    legacy one): the bucket count is folded into the manifest signature,
    so the stale state is discarded and the tokenizer runs again."""
    corpus, legacy_dir, _ = ref
    out = str(tmp_path / "idx")
    faults.install(faults.parse_plan("crash.pass3:once@1"))
    try:
        with pytest.raises(faults.InjectedCrash):
            build_index_streaming([corpus], out, radix_buckets=4,
                                  **BUILD_KW)
    finally:
        faults.clear()
    tokenized = {"n": 0}
    def counting(*a, **kw):
        tokenized["n"] += 1
        return _REAL_TOKENIZER(*a, **kw)
    monkeypatch.setattr(streaming, "make_chunked_tokenizer", counting)
    build_index_streaming([corpus], out, radix_buckets=8, **BUILD_KW)
    assert tokenized["n"] == 1
    assert_identical(out, legacy_dir)


# ---------------------------------------------------------------------------
# multiprocess tokenizer: byte parity + fault-plan inheritance
# ---------------------------------------------------------------------------


def _collect_deltas(paths, procs, k=1, chunk_bytes=900, batch_docs=40):
    from tpu_ir.analysis.native import PyChunkedTokenizer

    tok = PyChunkedTokenizer(paths, k=k, batch_docs=batch_docs,
                             chunk_bytes=chunk_bytes, procs=procs)
    deltas = list(tok.deltas())
    vocab = tok.vocab()
    tok.close()
    return deltas, vocab


@pytest.mark.parametrize("k", [1, 2])
def test_tokenizer_pool_parity(tmp_path, k):
    """TPU_IR_TOKENIZE_PROCS=1 vs N: identical deltas (docids, temp
    ids, lengths), identical chunk boundaries, identical vocab — over a
    multi-file corpus whose documents straddle the chunk threshold."""
    c1 = write_corpus(tmp_path / "a.trec", n_docs=90)
    c2 = write_corpus(tmp_path / "b.trec", n_docs=45, skew=3,
                      prefix="B")
    serial, v1 = _collect_deltas([c1, c2], procs=1, k=k)
    pooled, v3 = _collect_deltas([c1, c2], procs=3, k=k)
    assert v1 == v3
    assert len(serial) > 2  # chunking actually split the corpus
    assert len(serial) == len(pooled)
    for a, b in zip(serial, pooled):
        assert a[0] == b[0]
        assert np.array_equal(a[1], b[1])
        assert np.array_equal(a[2], b[2])


def test_tokenizer_pool_byte_identical_spills(tmp_path, monkeypatch, ref):
    """End to end: a radix build through the POOLED pure-Python
    tokenizer produces byte-identical artifacts (the pool satellite's
    'byte-identical token spills' claim, proven at the artifact level
    where it matters)."""
    corpus, legacy_dir, _ = ref
    from tpu_ir.analysis.native import PyChunkedTokenizer

    out = str(tmp_path / "idx")
    monkeypatch.setattr(
        streaming, "make_chunked_tokenizer",
        lambda paths, k=1, with_text=False, procs=None, **kw:
            PyChunkedTokenizer(paths, k=k, with_text=with_text,
                               procs=2))
    build_index_streaming([corpus], out, radix_buckets=4,
                          tokenize_procs=2, **BUILD_KW)
    assert_identical(out, legacy_dir)


def test_pool_workers_inherit_fault_plan(tmp_path, monkeypatch):
    """The pool initializer re-installs the parent's TPU_IR_FAULTS spec
    in every worker: a key-matched rule on the tokenize.pool site fires
    on its chunk regardless of which worker draws it, and surfaces as a
    normal exception in the parent (not a worker death)."""
    corpus = write_corpus(tmp_path / "c.trec", n_docs=60)
    monkeypatch.setenv("TPU_IR_FAULTS", "tokenize.pool@chunk=1:always")
    faults.clear()  # re-arm env pickup
    try:
        with pytest.raises(OSError, match="injected tokenizer pool"):
            _collect_deltas([corpus], procs=2)
    finally:
        monkeypatch.delenv("TPU_IR_FAULTS")
        faults.clear()


# ---------------------------------------------------------------------------
# bucket-segmented parts (TPU_IR_RADIX_PARTS)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def bucketed(ref, tmp_path_factory):
    corpus, legacy_dir, _ = ref
    out = str(tmp_path_factory.mktemp("bparts") / "idx")
    build_index_streaming([corpus], out, radix_buckets=4,
                          radix_parts=True, **BUILD_KW)
    return corpus, legacy_dir, out


def test_bucketed_parts_verify_and_dictionary(bucketed):
    _, _, out = bucketed
    r = verify_index(out)
    assert r["ok"]
    # the layout is genuinely segmented (terms not globally sorted)...
    assert r["bucket_segmented_shards"] > 0
    # ...and the dictionary's offsets point into the REAL part layout
    z = fmt.load_shard(out, 0)
    assert not (np.diff(z["term_ids"]) > 0).all()


def test_bucketed_parts_scorer_matches_canonical(bucketed):
    _, legacy_dir, out = bucketed
    s_canon = Scorer.load(legacy_dir)
    s_b = Scorer.load(out)
    for q in ["salmon fishing", "quick brown fox", "stock market",
              "honey bears"]:
        assert s_b.search(q) == s_canon.search(q), q
        assert (s_b.search_batch([q], scoring="bm25")
                == s_canon.search_batch([q], scoring="bm25")), q


def test_bucketed_parts_migrate_and_inspect(bucketed, capsys):
    _, _, out = bucketed
    from tpu_ir.cli import main as cli_main

    from tpu_ir.index.migrate import migrate_index

    migrate_index(out, to_version=1)
    assert verify_index(out)["ok"]
    migrate_index(out, to_version=2)
    assert verify_index(out)["ok"]
    assert cli_main(["inspect", out]) == 0
    capsys.readouterr()
    assert cli_main(["verify", out]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["bucket_segmented_shards"] > 0


# ---------------------------------------------------------------------------
# pipeline plumbing
# ---------------------------------------------------------------------------


def test_prefetch_iter_order_and_exceptions():
    from tpu_ir.utils.transfer import prefetch_iter

    assert list(prefetch_iter(iter(range(50)), depth=4)) == list(range(50))

    def boom():
        yield 1
        yield 2
        raise RuntimeError("producer died")

    got = []
    with pytest.raises(RuntimeError, match="producer died"):
        for x in prefetch_iter(boom(), depth=2):
            got.append(x)
    assert got == [1, 2]

    # InjectedCrash (a BaseException) propagates like a real death
    def crash():
        yield 1
        raise faults.InjectedCrash("mid-pass death")

    with pytest.raises(faults.InjectedCrash):
        list(prefetch_iter(crash(), depth=2))

    # early consumer exit unblocks a parked producer (no thread leak —
    # the conftest leak guard enforces the rest)
    for x in prefetch_iter(iter(range(1000)), depth=2):
        if x == 3:
            break


def test_radix_env_knob_default(tmp_path, monkeypatch, ref):
    """TPU_IR_RADIX_BUCKETS switches the default build path; artifacts
    stay bit-identical so operators can flip it fleet-wide."""
    corpus, legacy_dir, _ = ref
    monkeypatch.setenv("TPU_IR_RADIX_BUCKETS", "4")
    out = str(tmp_path / "idx")
    build_index_streaming([corpus], out, **BUILD_KW)
    assert_identical(out, legacy_dir)
    # the build keeps no spills on success, so prove the radix path
    # actually ran via the job report's recorded config
    jobs_dir = os.path.join(out, "jobs")
    name = next(n for n in os.listdir(jobs_dir)
                if n.startswith("TermKGramDocIndexer"))
    with open(os.path.join(jobs_dir, name)) as f:
        rep = json.load(f)
    assert rep["config"]["radix_buckets"] == 4


def test_positions_falls_back_to_legacy_pass2(tmp_path, ref):
    """positions=True needs each doc's flat token order, which the
    radix partition destroys — the build must fall back (loudly) to the
    per-batch pass 2 and still produce a valid positional index."""
    corpus, _, _ = ref
    out = str(tmp_path / "idx")
    meta = build_index_streaming([corpus], out, radix_buckets=8,
                                 positions=True, **BUILD_KW)
    assert meta.has_positions
    assert verify_index(out)["ok"]


def test_split_half_merge_over_radix_sources(tmp_path, ref):
    """The satellite triangle: radix build == one-shot build ==
    split-half merge. Halves are built through the RADIX path (one of
    them with bucket-segmented parts — merge expands per-term runs and
    union-lexsorts, so part-internal order is irrelevant) and the merge
    must be byte-identical to the one-shot index of the whole corpus."""
    from tpu_ir.index.merge import merge_indexes

    corpus, _, oneshot_dir = ref
    text = open(corpus).read()
    docs = text.split("</DOC>\n")[:-1]
    half = len(docs) // 2
    a = tmp_path / "a.trec"
    b = tmp_path / "b.trec"
    a.write_text("</DOC>\n".join(docs[:half]) + "</DOC>\n")
    b.write_text("</DOC>\n".join(docs[half:]) + "</DOC>\n")
    ia, ib = str(tmp_path / "ia"), str(tmp_path / "ib")
    build_index_streaming([str(a)], ia, radix_buckets=4, **BUILD_KW)
    build_index_streaming([str(b)], ib, radix_buckets=4,
                          radix_parts=True, **BUILD_KW)
    merged = str(tmp_path / "merged")
    merge_indexes([ia, ib], merged, num_shards=3)
    assert_identical(merged, oneshot_dir)


def test_radix_parts_flip_never_resumes(tmp_path, monkeypatch, ref):
    """radix_parts is folded into the resume signature: a crashed
    segmented-parts build restarted WITHOUT the flag must rebuild from
    scratch (tokenizer runs, stale segmented parts wiped) and converge
    on canonical bytes — not keep shard 0 segmented while the
    dictionary is written with canonical offsets."""
    corpus, legacy_dir, _ = ref
    out = str(tmp_path / "idx")
    faults.install(faults.parse_plan("crash.pass3:once@2"))
    try:
        with pytest.raises(faults.InjectedCrash):
            build_index_streaming([corpus], out, radix_buckets=4,
                                  radix_parts=True, **BUILD_KW)
    finally:
        faults.clear()
    z = fmt.load_shard(out, 0)  # the crashed run left a segmented part
    assert not (np.diff(z["term_ids"]) > 0).all()

    tokenized = {"n": 0}

    def counting(*a, **kw):
        tokenized["n"] += 1
        return _REAL_TOKENIZER(*a, **kw)

    monkeypatch.setattr(streaming, "make_chunked_tokenizer", counting)
    build_index_streaming([corpus], out, radix_buckets=4,
                          radix_parts=False, **BUILD_KW)
    assert tokenized["n"] == 1
    assert_identical(out, legacy_dir)
