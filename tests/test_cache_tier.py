"""Zipf workload engine + generation-keyed result-cache tier (ISSUE 15).

The contract under test:

- the Workload model is SEEDED (replayable), its skew actually
  concentrates the draw, and skew 0 is a uniform control;
- a cache hit is BIT-IDENTICAL to the miss path — docids, float bits,
  tie order — across tiered(sparse)/sharded layouts x tfidf/bm25 x
  rerank, at both the frontend and the router;
- a generation swap invalidates BY KEY: zero stale-generation cache
  responses (every cached response's generation matches a known
  manifest, and post-swap lookups answer the new generation);
- cache-aware hedging: a request served from cache never arms a hedge
  timer and never pollutes the per-shard trailing-RTT window;
- eviction is LRU under the bounded capacity (pinned at capacity 1);
- TPU_IR_MERGE_AUTO=0 + `tpu-ir compact` reach an end state pinned
  equivalent (metadata checksums) to inline auto-merge.
"""

import json
import random

import numpy as np
import pytest

from tpu_ir.index.builder import build_index
from tpu_ir.search import Scorer
from tpu_ir.serving import (
    Overloaded,
    ResultCache,
    Router,
    RouterConfig,
    ServingConfig,
    ServingFrontend,
    Workload,
    make_queries,
    rolling_swap,
    run_distributed_soak,
    serve_worker,
)
from tpu_ir import obs

WORDS = ("salmon fishing river bears honey quick brown fox lazy dog "
         "market investor asset bond stock season rain forest".split())

N_SHARDS = 2


def _write_corpus(path, n_docs=80):
    body = []
    for i in range(n_docs):
        text = " ".join(WORDS[(i + j) % len(WORDS)]
                        for j in range(3 + (i % 5)))
        body.append(f"<DOC>\n<DOCNO> D-{i:04d} </DOCNO>\n<TEXT>\n"
                    f"{text}\n</TEXT>\n</DOC>\n")
    path.write_text("".join(body))
    return str(path)


@pytest.fixture(scope="module")
def index_dir(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("cache_tier")
    corpus = _write_corpus(tmp / "corpus.trec")
    out = str(tmp / "idx")
    build_index([corpus], out, num_shards=2, compute_chargrams=False)
    return out


@pytest.fixture(scope="module")
def scorers(index_dir):
    return {layout: Scorer.load(index_dir, layout=layout)
            for layout in ("sparse", "sharded")}


# ---------------------------------------------------------------------------
# the workload model
# ---------------------------------------------------------------------------


def test_workload_seeded_and_shaped(scorers):
    sc = scorers["sparse"]
    w1 = Workload.from_scorer(sc, kind="zipf", skew=1.1, seed=7)
    w2 = Workload.from_scorer(sc, kind="zipf", skew=1.1, seed=7)
    q1, q2 = w1.make_queries(50), w2.make_queries(50)
    assert q1 == q2, "same seed must replay the same workload"
    # the request-dict shape matches the legacy soak maker
    assert set(q1[0]) == {"text", "scoring", "rerank", "k"}
    assert all(1 <= len(r["text"].split()) <= 3 for r in q1)
    # uniform kind resolves to None -> the legacy draw
    assert Workload.from_scorer(sc, kind="uniform") is None


def test_workload_skew_concentrates_the_draw(scorers):
    """At s=1.5 the head term dominates; at s=0 the draw is uniform —
    the property the per-skew bench rows ride on."""
    sc = scorers["sparse"]
    rng = random.Random(0)

    def head_share(skew):
        w = Workload.from_scorer(sc, kind="zipf", skew=skew, seed=0)
        counts: dict = {}
        for _ in range(2000):
            t = w.draw_term(rng)
            counts[t] = counts.get(t, 0) + 1
        return max(counts.values()) / 2000.0, len(counts)

    hot_share, hot_distinct = head_share(1.5)
    uni_share, uni_distinct = head_share(0.0)
    assert hot_share > 3 * uni_share, (hot_share, uni_share)
    assert hot_distinct <= uni_distinct
    # exact-repeat queries appear under skew — the cache's fuel
    w = Workload.from_scorer(sc, kind="zipf", skew=1.5, seed=0)
    texts = [r["text"] for r in w.make_queries(200)]
    assert len(set(texts)) < len(texts)


def test_workload_burst_schedule():
    w = Workload(["a", "b"], burst=1.0)
    scales = [w.pacing_scale(f / 100.0) for f in range(100)]
    assert min(scales) < 0.8 < 1.2 < max(scales)
    flat = Workload(["a", "b"], burst=0.0)
    assert all(flat.pacing_scale(f / 10.0) == 1.0 for f in range(10))


def test_make_queries_env_workload(scorers, monkeypatch):
    """TPU_IR_WORKLOAD=zipf reshapes the soak's query maker; unset, the
    legacy uniform draw is byte-reproducible (history comparability)."""
    sc = scorers["sparse"]
    monkeypatch.delenv("TPU_IR_WORKLOAD", raising=False)
    legacy = make_queries(sc, 20, seed=3)
    monkeypatch.setenv("TPU_IR_WORKLOAD", "zipf")
    monkeypatch.setenv("TPU_IR_WORKLOAD_SKEW", "1.3")
    zipf = make_queries(sc, 20, seed=3)
    assert zipf != legacy
    monkeypatch.delenv("TPU_IR_WORKLOAD")
    assert make_queries(sc, 20, seed=3) == legacy


# ---------------------------------------------------------------------------
# ResultCache units
# ---------------------------------------------------------------------------


def test_cache_lru_eviction_at_capacity_one():
    c = ResultCache(1, name="t")
    c.put(("a",), 1, generation=0)
    assert c.get(("a",)) == 1
    c.put(("b",), 2, generation=0)          # evicts a
    assert obs.get_registry().get("cache.evict") == 1
    assert c.get(("a",)) is None
    assert c.get(("b",)) == 2
    assert len(c) == 1


def test_cache_generation_bump_purges_and_refuses_old():
    c = ResultCache(8, name="t")
    c.put(("a",), 1, generation=1)
    c.put(("b",), 2, generation=2)
    assert c.bump_generation(2) == 1        # only gen-1 purged
    assert obs.get_registry().get("cache.stale_generation") == 1
    assert c.get(("b",)) == 2
    # a slow miss completing after the swap cannot resurrect gen 1
    c.put(("c",), 3, generation=1)
    assert c.get(("c",)) is None
    # the bump is monotonic
    assert c.bump_generation(1) == 0
    assert c.generation() == 2


def test_cache_disabled_is_inert():
    c = ResultCache(0, name="t")
    before = obs.get_registry().get("cache.miss")
    c.put(("a",), 1, generation=0)
    assert c.get(("a",)) is None
    assert not c.enabled
    assert obs.get_registry().get("cache.miss") == before


# ---------------------------------------------------------------------------
# THE property: frontend hit == miss, bit-identical
# ---------------------------------------------------------------------------


QUERIES = ["salmon fishing", "bears honey market", "quick",
           "dog dog salmon", "rain forest investor"]


@pytest.mark.parametrize("layout", ["sparse", "sharded"])
def test_frontend_hit_bitidentical_to_miss(scorers, layout):
    """Across layouts x scorings x rerank: the second (cached) response
    carries the exact tuples of the first (missed) one — and a fresh
    no-cache frontend agrees, so the hit IS the miss path's bits."""
    sc = scorers[layout]
    fe = ServingFrontend(sc, ServingConfig(cache_entries=128))
    bare = ServingFrontend(sc, ServingConfig(cache_entries=0))
    assert bare.cache is None
    reg = obs.get_registry()
    for scoring in ("tfidf", "bm25"):
        for rerank in (None, 10):
            for q in QUERIES:
                miss = fe.search(q, k=5, scoring=scoring, rerank=rerank)
                hits_before = reg.get("cache.hit")
                hit = fe.search(q, k=5, scoring=scoring, rerank=rerank)
                assert reg.get("cache.hit") == hits_before + 1
                assert list(hit) == list(miss), (layout, scoring, q)
                ref = bare.search(q, k=5, scoring=scoring, rerank=rerank)
                assert list(hit) == list(ref), (layout, scoring, q)
                assert hit.level == "full" and not hit.degraded


def test_frontend_key_separates_routes(scorers):
    """k / scoring / rerank each mint distinct keys — a hit can never
    answer a request the miss path would route differently."""
    sc = scorers["sparse"]
    fe = ServingFrontend(sc, ServingConfig(cache_entries=128))
    reg = obs.get_registry()
    fe.search("salmon fishing", k=5, scoring="bm25")
    for kwargs in ({"k": 10, "scoring": "bm25"},
                   {"k": 5, "scoring": "tfidf"},
                   {"k": 5, "scoring": "bm25", "rerank": 10}):
        before = reg.get("cache.hit")
        fe.search("salmon fishing", **kwargs)
        assert reg.get("cache.hit") == before, kwargs


def test_frontend_uncacheable_texts_bypass(scorers):
    """Glob/fuzzy operators expand against the vocabulary — the key
    must not collide them with literal terms; they bypass entirely."""
    sc = scorers["sparse"]
    fe = ServingFrontend(sc, ServingConfig(cache_entries=128))
    reg = obs.get_registry()
    for q in ("salm*", "salmn~"):
        fe.search(q, k=5, scoring="bm25")
        fe.search(q, k=5, scoring="bm25")
    assert reg.get("cache.hit") == 0
    assert reg.get("cache.miss") == 0
    assert len(fe.cache) == 0


def test_frontend_normalized_terms_share_one_entry(scorers):
    """The frontend key is the ANALYZED term-id sequence: whitespace
    and case variants of one query share one entry; term ORDER does
    not (float accumulation follows slot order)."""
    sc = scorers["sparse"]
    fe = ServingFrontend(sc, ServingConfig(cache_entries=128))
    reg = obs.get_registry()
    first = fe.search("salmon fishing", k=5, scoring="bm25")
    for variant in ("  salmon   fishing ", "Salmon FISHING"):
        before = reg.get("cache.hit")
        res = fe.search(variant, k=5, scoring="bm25")
        assert reg.get("cache.hit") == before + 1, variant
        assert list(res) == list(first)
    # reversed term order is a DIFFERENT key (and may be different bits)
    before = reg.get("cache.hit")
    fe.search("fishing salmon", k=5, scoring="bm25")
    assert reg.get("cache.hit") == before


def test_frontend_generation_swap_invalidates_by_key(tmp_path):
    """A live-index reload moves the key space: the first post-swap
    request MISSES and answers the new generation's bits; the old
    entries are purged as cache.stale_generation."""
    from tpu_ir.index.ingest import IngestWriter
    from tpu_ir.index.segments import LiveIndex

    live = str(tmp_path / "live")
    LiveIndex.create(live, num_shards=2)
    rng = random.Random(5)
    with IngestWriter(live, auto_merge=False) as w:
        for i in range(30):
            w.add(f"D-{i:03d}",
                  " ".join(rng.choice(WORDS) for _ in range(5)))
        w.compact_all(note="gen A")
    gen_a = LiveIndex.open(live).current_gen()
    fe = ServingFrontend(Scorer.load_generation(live, layout="sparse"),
                         ServingConfig(cache_entries=64))
    q = "salmon fishing"
    r_a = fe.search(q, k=5, scoring="bm25")
    assert r_a.generation == gen_a
    assert fe.search(q, k=5, scoring="bm25").generation == gen_a
    reg = obs.get_registry()
    assert reg.get("cache.hit") == 1

    with IngestWriter(live, auto_merge=False) as w:
        for i in range(4):
            w.update(f"D-{i:03d}",
                     " ".join(rng.choice(WORDS) for _ in range(5)))
        w.compact_all(note="gen B")
    gen_b = LiveIndex.open(live).current_gen()
    fe.reload_generation()
    assert reg.get("cache.stale_generation") >= 1
    hits_before = reg.get("cache.hit")
    r_b = fe.search(q, k=5, scoring="bm25")
    assert reg.get("cache.hit") == hits_before  # a MISS, by key
    assert r_b.generation == gen_b
    ref_b = Scorer.load_generation(live, gen_b, layout="sparse")
    assert list(r_b) == list(ref_b.search_batch([q], k=5,
                                                scoring="bm25")[0])
    # and the new generation's entry serves hits again
    assert fe.search(q, k=5, scoring="bm25").generation == gen_b
    assert reg.get("cache.hit") == hits_before + 1


# ---------------------------------------------------------------------------
# the router cache: no fan-out, no hedge, no RTT pollution
# ---------------------------------------------------------------------------


@pytest.fixture()
def http_workers(index_dir):
    started = [serve_worker(index_dir, s, N_SHARDS, layout="sparse",
                            warm=False) for s in range(N_SHARDS)]
    yield [[f"127.0.0.1:{srv.port}"] for srv, _, _ in started]
    for srv, _, _ in started:
        srv.stop()


def test_router_hit_bitidentical_and_skips_fanout(index_dir, scorers,
                                                  http_workers):
    ref = scorers["sparse"]
    reg = obs.get_registry()
    with Router(index_dir, http_workers,
                RouterConfig(deadline_ms=30000,
                             cache_entries=64)) as router:
        for scoring in ("tfidf", "bm25"):
            for q in QUERIES[:3]:
                full = list(ref.search_batch([q], k=5,
                                             scoring=scoring)[0])
                miss = router.search(q, k=5, scoring=scoring)
                rtts_before = [len(st._rtts) for st in router._stats]
                hits_before = reg.get("cache.hit")
                hit = router.search(q, k=5, scoring=scoring)
                assert reg.get("cache.hit") == hits_before + 1
                # bit-identical to the miss path AND the single-process
                # oracle — docids, float bits, tie order
                assert list(hit) == list(miss) == full, (scoring, q)
                assert Router.classify(hit) == "full"
                assert hit.shards_ok == tuple(range(N_SHARDS))
                assert hit.hedges == 0
                # no worker RPC ran: the trailing-RTT hedge source saw
                # NOTHING (cache-aware hedging's no-pollution half)
                assert [len(st._rtts) for st in router._stats] \
                    == rtts_before
        # two-phase rerank rides the same cache
        q = QUERIES[0]
        miss = router.search(q, k=5, rerank=10)
        hit = router.search(q, k=5, rerank=10)
        assert list(hit) == list(miss)
        # conservation: requests == served_full here (nothing shed)
        assert reg.get("router.requests") \
            == reg.get("router.served_full")
        # the health view carries the cache section
        h = router.health_summary()
        assert h["cache"]["entries"] == len(router.cache)
        assert h["cache"]["cache.hit"] == reg.get("cache.hit")


def test_router_hit_never_arms_hedge_timer(index_dir):
    """A slow primary makes the miss path hedge; the cached repeat must
    fire ZERO hedges (the hedge timer is never armed — there is no
    fan-out to hedge)."""
    import time as _time

    from tpu_ir.obs.server import MetricsServer

    calls = []

    def slow_search(payload):
        calls.append(1)
        _time.sleep(0.4)
        return {"hits": [[1, 3.0]], "level": "full", "degraded": False}

    def fast_search(payload):
        calls.append(1)
        return {"hits": [[1, 3.0]], "level": "full", "degraded": False}

    slow = MetricsServer(rpc_handlers={"search": slow_search}).start()
    fast = MetricsServer(rpc_handlers={"search": fast_search}).start()
    reg = obs.get_registry()
    try:
        with Router(index_dir,
                    [[f"127.0.0.1:{slow.port}",
                      f"127.0.0.1:{fast.port}"]],
                    RouterConfig(deadline_ms=10000, hedge_ms=50.0,
                                 cache_entries=16)) as router:
            router._stats[0]._cursor = 1  # slow replica is primary
            miss = router.search("whatever", k=5, return_docids=False)
            assert reg.get("router.hedge_fired") == 1
            assert miss.hedges == 1
            calls_before = len(calls)
            hit = router.search("whatever", k=5, return_docids=False)
            # no hedge fired, no worker dialed, same bits
            assert reg.get("router.hedge_fired") == 1
            assert hit.hedges == 0
            assert len(calls) == calls_before
            assert list(hit) == list(miss)
    finally:
        slow.stop()
        fast.stop()


def test_router_swap_zero_stale_generation_responses(tmp_path):
    """The swap acceptance, in-process: entries cached at gen A, the
    fleet rolls to gen B, the swap driver calls note_generation — the
    very next lookup answers gen B's bits. Every cached response's
    generation matches a known manifest throughout (zero stale)."""
    from tpu_ir.index.ingest import IngestWriter
    from tpu_ir.index.segments import LiveIndex

    live = str(tmp_path / "live")
    LiveIndex.create(live, num_shards=2)
    rng = random.Random(9)
    with IngestWriter(live, auto_merge=False) as w:
        for i in range(30):
            w.add(f"D-{i:03d}",
                  " ".join(rng.choice(WORDS) for _ in range(5)))
        w.compact_all(note="gen A")
    gen_a = LiveIndex.open(live).current_gen()
    with IngestWriter(live, auto_merge=False) as w:
        for i in range(4):
            w.update(f"N-{i:03d}",
                     " ".join(rng.choice(WORDS) for _ in range(5)))
        w.compact_all(note="gen B")
    gen_b = LiveIndex.open(live).current_gen()

    workers = [serve_worker(live, s, 2, index_generation=gen_a,
                            warm=False) for s in range(2)]
    servers = [w[0] for w in workers]
    grid = [[f"127.0.0.1:{srv.port}"] for srv in servers]
    reg = obs.get_registry()
    known = {gen_a, gen_b}
    try:
        with Router(live, grid,
                    RouterConfig(deadline_ms=10000, health_ttl_s=0.0,
                                 cache_entries=64)) as router:
            q = "salmon fishing"
            r0 = router.search(q, k=5, scoring="bm25")
            r1 = router.search(q, k=5, scoring="bm25")  # cached, gen A
            assert r0.generation == r1.generation == gen_a
            assert reg.get("cache.hit") == 1
            # the rolling swap + the driver's note to the router
            out = rolling_swap(grid, generation=gen_b)
            assert not out["failed"]
            assert router.note_generation(gen_b) >= 1
            assert reg.get("cache.stale_generation") >= 1
            # first post-swap request: a MISS answering gen B's bits
            hits_before = reg.get("cache.hit")
            r2 = router.search(q, k=5, scoring="bm25")
            assert reg.get("cache.hit") == hits_before
            assert r2.generation == gen_b
            ref_b = Scorer.load_generation(live, gen_b, layout="sparse")
            assert list(r2) == list(ref_b.search_batch(
                [q], k=5, scoring="bm25")[0])
            # and the repeat is a hit on the NEW generation
            r3 = router.search(q, k=5, scoring="bm25")
            assert reg.get("cache.hit") == hits_before + 1
            assert r3.generation == gen_b and list(r3) == list(r2)
            for r in (r0, r1, r2, r3):
                assert r.generation in known
    finally:
        for srv in servers:
            srv.stop()


# ---------------------------------------------------------------------------
# the distributed acceptance: zipf traffic + cache through real workers
# ---------------------------------------------------------------------------


def test_distributed_soak_zipf_with_cache(index_dir, tmp_path):
    """The measured-regime pin: a routed soak under Zipf traffic with
    the router cache on — conservation holds, every full response
    (cached or routed) is bit-identical to the serial reference, and
    the skewed head actually HITS (hit_fraction > 0)."""
    report = run_distributed_soak(
        index_dir, shards=2, replicas=1, threads=6, queries=80,
        seed=0, chaos=False,
        workload={"kind": "zipf", "skew": 1.2, "burst": 0.0},
        cache_entries=256,
        worker_deadline_s=3.0,
        router_config=RouterConfig(deadline_ms=8000.0, max_queue=128),
        rundir=str(tmp_path / "run"),
        flight_dir=str(tmp_path / "flight"),
        recovery_timeout_s=60.0)
    assert report["served"] + report["shed"] == report["submitted"]
    assert report["errors"] == 0, report["error_samples"]
    assert report["deadlocked"] == 0
    assert report["full_mismatches"] == 0
    assert report["partial_mismatches"] == 0
    assert report["unknown_generation"] == 0
    wl = report["workload"]
    assert wl["kind"] == "zipf" and wl["skew"] == 1.2
    assert wl["seed"] == 0 and wl["burst"] == 0.0
    cache = report["cache"]
    assert cache["hit"] > 0, cache
    assert cache["hit_fraction"] > 0.0
    assert cache["stale_generation"] == 0
    assert report["recovery_full"] == report["recovery_probes"]


# ---------------------------------------------------------------------------
# residency hint + df skew
# ---------------------------------------------------------------------------


def test_df_skew_report_math():
    from tpu_ir.index.doctor import df_skew_report

    # 10 terms: one holds 91 of 100 postings -> decile share 0.91
    df = np.array([91, 1, 1, 1, 1, 1, 1, 1, 1, 1])
    rep = df_skew_report(df)
    assert rep["nonzero_terms"] == 10
    assert rep["top_decile_terms"] == 1
    assert rep["top_decile_postings_share"] == pytest.approx(0.91)
    empty = df_skew_report(np.zeros(4, np.int64))
    assert empty["top_decile_postings_share"] is None


def test_prewarm_residency_is_pure_warmup(scorers):
    from tpu_ir.serving import prewarm_hot_residency

    sc = scorers["sparse"]
    before = [list(sc.search_batch([q], k=5, scoring=s)[0])
              for q in QUERIES for s in ("tfidf", "bm25")]
    rep = prewarm_hot_residency(sc, mode="1")
    assert rep["engaged"] is True
    assert any(w.startswith("strip.") for w in rep["warmed"]), rep
    after = [list(sc.search_batch([q], k=5, scoring=s)[0])
             for q in QUERIES for s in ("tfidf", "bm25")]
    assert after == before  # a hint can never change a bit
    off = prewarm_hot_residency(sc, mode="0")
    assert off["engaged"] is False and not off["warmed"]


def test_doctor_reports_df_skew(index_dir, capsys):
    from tpu_ir.cli import main

    assert main(["doctor", index_dir]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    skew = out["df"]["skew"]
    assert skew["nonzero_terms"] > 0
    assert 0.0 <= skew["top_decile_postings_share"] <= 1.0


def test_worker_healthz_carries_residency(index_dir):
    srv, fe, sc = serve_worker(index_dir, 0, 2, layout="sparse",
                               warm=True)
    try:
        from tpu_ir.serving.shardset import get_worker_health

        h = get_worker_health(f"127.0.0.1:{srv.port}", 5.0)
        res = h["worker"]["residency"]
        assert "engaged" in res and "top_decile_postings_share" in res
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# satellite: TPU_IR_MERGE_AUTO=0 + tpu-ir compact — equivalent end state
# ---------------------------------------------------------------------------


def _ingest_in_batches(live, docs, monkeypatch=None):
    from tpu_ir.index.ingest import IngestWriter
    from tpu_ir.index.segments import LiveIndex

    LiveIndex.create(live, num_shards=2)
    with IngestWriter(live, buffer_docs=4) as w:
        for docid, text in docs:
            w.add(docid, text)
    return LiveIndex.open(live)


def test_merge_auto_off_defers_and_compact_drains(tmp_path, monkeypatch):
    rng = random.Random(11)
    docs = [(f"D-{i:03d}", " ".join(rng.choice(WORDS) for _ in range(5)))
            for i in range(24)]

    # inline auto-merge (the default): flushes amortize debt as they go
    monkeypatch.delenv("TPU_IR_MERGE_AUTO", raising=False)
    live_auto = _ingest_in_batches(str(tmp_path / "auto"), docs)

    # decoupled: flushes never merge; debt accumulates
    monkeypatch.setenv("TPU_IR_MERGE_AUTO", "0")
    live_defer = _ingest_in_batches(str(tmp_path / "defer"), docs)
    n_defer = len(live_defer.manifest()["segments"])
    assert n_defer > len(live_auto.manifest()["segments"])
    assert n_defer == 6  # one segment per 4-doc flush, untouched

    # `tpu-ir compact` drains the deferred debt explicitly
    from tpu_ir.cli import main

    assert main(["compact", str(tmp_path / "defer")]) == 0
    drained = live_defer.manifest()
    assert len(drained["segments"]) < n_defer

    # pinned-equivalent end state: full compaction of both paths yields
    # the SAME canonical artifacts (metadata checksums equal) — the
    # merge order never leaks into the bytes
    from tpu_ir.index import format as fmt
    from tpu_ir.index.segments import compact, resolve_serving

    compact(live_auto)
    compact(live_defer)
    metas = []
    for d in (str(tmp_path / "auto"), str(tmp_path / "defer")):
        resolved, _ = resolve_serving(d)
        metas.append(fmt.IndexMetadata.load(resolved))
    assert metas[0].num_docs == metas[1].num_docs == len(docs)
    assert metas[0].checksums == metas[1].checksums


def test_compact_cli_all_and_non_live(tmp_path, capsys):
    from tpu_ir.cli import main
    from tpu_ir.index.segments import LiveIndex

    assert main(["compact", str(tmp_path / "nope")]) == 1
    rng = random.Random(2)
    docs = [(f"D-{i:02d}", " ".join(rng.choice(WORDS) for _ in range(4)))
            for i in range(9)]
    live = str(tmp_path / "live")
    _ingest_in_batches(live, docs)
    capsys.readouterr()
    assert main(["compact", live, "--all"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["mode"] == "all"
    assert len(out["segments"]) == 1
    assert LiveIndex.open(live).doc_counts()["live"] == 9


# ---------------------------------------------------------------------------
# CLI / bench-check wiring
# ---------------------------------------------------------------------------


def test_serve_bench_skew_validation(index_dir):
    from tpu_ir.cli import main

    assert main(["serve-bench", index_dir, "--workload", "zipf",
                 "--skew", "-1", "--shards", "2"]) == 2
    assert main(["serve-bench", index_dir, "--workload", "zipf",
                 "--skew", "0,0.7", "--threads", "2",
                 "--queries", "8"]) == 2  # multi-skew needs --shards


def test_bench_check_gates_cache_hit_fraction():
    from tpu_ir.obs.bench_check import METRICS, check_history

    assert "cache_hit_fraction" in METRICS
    base = {"config": "serve_routed-100q-s2r1-zipf1.1", "backend": "cpu",
            "routed_qps": 100.0, "cache_hit_fraction": 0.5}
    rows = [dict(base) for _ in range(4)]
    rows.append(dict(base, cache_hit_fraction=0.05))
    rep = check_history(rows, window=8, min_rows=3, tolerance=0.3)
    assert rep["status"] == "breach"
    assert [b["metric"] for b in rep["breaches"]] \
        == ["cache_hit_fraction"]


def test_cache_cli_stats_and_clear(scorers, capsys):
    from tpu_ir.cli import main

    fe = ServingFrontend(scorers["sparse"],
                         ServingConfig(cache_entries=16))
    fe.search("salmon fishing", k=5, scoring="bm25")
    fe.search("salmon fishing", k=5, scoring="bm25")
    assert main(["cache"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["counters"]["cache.hit"] == 1
    assert any(c["name"] == "frontend" and c["entries"] == 1
               for c in out["caches"])
    assert main(["cache", "clear"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["cleared_entries"] >= 1
    assert len(fe.cache) == 0
    assert obs.get_registry().get("cache.hit") == 0
