"""Document store + snippets (VERDICT r3 item 6): the raw content the
reference discards at index time (Indexable.getContent,
edu/umd/cloud9/collection/Indexable.java:24-44) survives as a compressed
sidecar, and search renders query-highlighted text windows from it."""

import os
import zlib

import numpy as np
import pytest

from tpu_ir.cli import main
from tpu_ir.index import build_index
from tpu_ir.index.docstore import BLOCK_DOCS, DocStore, build_docstore
from tpu_ir.search import Scorer

DOCS = {
    "S-01": "salmon fishing is fun and salmon are tasty",
    "S-02": "fishing for trout while salmon swim upstream near the river "
            "bend where the water runs cold and clear all year round",
    "S-03": "quick brown fox jumps over the lazy dog",
    "S-04": "the market closed sharply lower on tuesday",
}


def write_corpus(tmp_path, docs=DOCS):
    p = tmp_path / "c.trec"
    p.write_text("".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in docs.items()))
    return str(p)


@pytest.fixture(scope="module")
def idx(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("docstore")
    corpus = write_corpus(tmp)
    out = str(tmp / "idx")
    build_index([corpus], out, k=1, num_shards=2, compute_chargrams=False)
    stats = build_docstore([corpus], out)
    return out, stats


def test_docstore_roundtrip(idx):
    out, stats = idx
    assert stats["docs"] == len(DOCS)
    assert 0 < stats["stored_bytes"] < stats["raw_bytes"]  # compressed
    store = DocStore(out)
    scorer = Scorer.load(out)
    for docid, text in DOCS.items():
        content = store.get(scorer.mapping.get_docno(docid))
        assert text in content and docid in content
    with pytest.raises(KeyError):
        store.get(999)
    store.close()


def test_docstore_many_blocks(tmp_path):
    """Docs spanning several compression blocks round-trip regardless of
    arrival-vs-docno order (perm indirection)."""
    docs = {f"Z-{i:04d}": f"document number {i} mentions token{i % 7}"
            for i in range(3 * 5 + 2)}
    corpus = write_corpus(tmp_path, docs)
    out = str(tmp_path / "idx")
    build_index([corpus], out, k=1, num_shards=2, compute_chargrams=False)
    build_docstore([corpus], out, block_docs=5)
    store = DocStore(out)
    scorer = Scorer.load(out)
    for docid, text in docs.items():
        assert text in store.get(scorer.mapping.get_docno(docid))


def test_docstore_corpus_mismatch(tmp_path):
    """A store built from a different corpus than the index must fail
    loudly, not silently mis-key snippets."""
    corpus = write_corpus(tmp_path)
    out = str(tmp_path / "idx")
    build_index([corpus], out, k=1, num_shards=2, compute_chargrams=False)
    other = tmp_path / "other.trec"
    other.write_text("<DOC>\n<DOCNO> X-1 </DOCNO>\n<TEXT>\nhi\n</TEXT>\n"
                     "</DOC>\n")
    with pytest.raises(ValueError, match="docno mapping"):
        build_docstore([str(other)], out)
    # and a partial corpus (fewer docs than the index) fails the count
    sub = tmp_path / "sub"
    sub.mkdir()
    partial = write_corpus(sub, dict(list(DOCS.items())[:2]))
    with pytest.raises(ValueError, match="corpus pass saw"):
        build_docstore([partial], out)


def test_snippet_highlights_and_windows(idx):
    out, _ = idx
    scorer = Scorer.load(out)
    # analyzed matching: 'fishing' stems to the query's 'fish'
    snip = scorer.snippet("fish", "S-01")
    assert "**fishing**" in snip and "S-01" not in snip
    # long doc: the window centers on the match cluster, with ellipses
    snip = scorer.snippet("water cold", "S-02")
    assert "**water**" in snip and "**cold**" in snip
    assert snip.startswith("... ") or snip.endswith(" ...")
    # no match: leading window, no marks
    snip = scorer.snippet("zebra", "S-03")
    assert "**" not in snip and snip.startswith("quick brown fox")
    # quoted queries highlight their component words
    snip = scorer.snippet('"salmon fishing"', "S-01")
    assert "**salmon**" in snip and "**fishing**" in snip


def test_snippets_without_store_errors(tmp_path):
    corpus = write_corpus(tmp_path)
    out = str(tmp_path / "idx")
    build_index([corpus], out, k=1, num_shards=2, compute_chargrams=False)
    with pytest.raises(ValueError, match="--store"):
        Scorer.load(out).snippet("salmon", "S-01")


def test_store_cli_end_to_end(tmp_path, capsys):
    corpus = write_corpus(tmp_path)
    out = str(tmp_path / "idx")
    assert main(["index", str(tmp_path), out, "--backend", "cpu",
                 "--shards", "2", "--no-chargrams", "--store"]) == 0
    assert '"docstore"' in capsys.readouterr().out
    assert main(["search", out, "--backend", "cpu", "-q", "salmon",
                 "--snippets", "--k", "2"]) == 0
    assert "**salmon**" in capsys.readouterr().out


class _CountingAnalyzer:
    """Wraps the real analyzer, counting analyze() calls — the snippet
    scan's unit of work (tokenize + stopwords + Porter2 per word)."""

    def __init__(self, analyzer):
        self._an = analyzer
        self.calls = 0

    def analyze(self, text):
        self.calls += 1
        return self._an.analyze(text)


def test_snippet_perfect_window_early_exit():
    """A multi-MB document whose query terms co-occur early must cost a
    handful of analyzer calls, not a full-document scan (VERDICT r4 weak
    #3). Fillers are DISTINCT words so memoization cannot hide an
    unbounded scan."""
    from tpu_ir.analysis.native import make_analyzer
    from tpu_ir.search.snippets import make_snippet

    filler = " ".join(f"zq{i:07d}x" for i in range(450_000))  # ~5 MB
    doc = f"<DOC><TEXT>salmon fishing season {filler}</TEXT></DOC>"
    assert len(doc) > 4_000_000
    an = _CountingAnalyzer(make_analyzer())
    snip = make_snippet(doc, {"salmon", "fish"}, an)
    assert "**salmon**" in snip and "**fishing**" in snip
    assert snip.endswith(" ...")
    # the full-coverage window is found at word 2; the scan stops at the
    # exact-region boundary instead of crawling 450k words
    from tpu_ir.search.snippets import SNIPPET_EXACT_WORDS
    assert an.calls < SNIPPET_EXACT_WORDS + 50

    # with a small exact region the bound is proportionally tight
    an2 = _CountingAnalyzer(make_analyzer())
    snip2 = make_snippet(doc, {"salmon", "fish"}, an2, exact_words=64)
    assert "**salmon**" in snip2 and "**fishing**" in snip2
    assert an2.calls < 120


def test_snippet_exact_region_keeps_densest_cluster():
    """Inside the exact region the densest-cluster selection is
    unchanged: a single-token query must still center on the later
    5-hit cluster, not early-exit on the first stray hit."""
    from tpu_ir.analysis.native import make_analyzer
    from tpu_ir.search.snippets import make_snippet

    doc = ("<DOC><TEXT>salmon intro mention " + "filler " * 60
           + "salmon feast salmon dinner salmon soup salmon roe salmon"
           + " tail</TEXT></DOC>")
    snip = make_snippet(doc, {"salmon"}, make_analyzer())
    assert snip.count("**salmon**") >= 4  # the cluster, not the stray


def test_snippet_scan_byte_cap():
    """When the query never fully co-occurs, the scan stops at the byte
    cap instead of crawling the whole record."""
    from tpu_ir.analysis.native import make_analyzer
    from tpu_ir.search.snippets import make_snippet

    filler = " ".join(f"zq{i:07d}x" for i in range(450_000))  # ~5 MB
    doc = f"<DOC><TEXT>salmon river {filler} fishing</TEXT></DOC>"
    an = _CountingAnalyzer(make_analyzer())
    snip = make_snippet(doc, {"salmon", "fish"}, an, scan_bytes=20_000)
    assert "**salmon**" in snip
    assert snip.endswith(" ...")  # truncation is visible
    # ~20 KB / ~11 bytes per filler word, plus slack
    assert an.calls < 4_000


def test_streaming_store_fold_no_second_corpus_read(tmp_path, monkeypatch):
    """build_index_streaming(store=True) writes the docstore from its
    pass-1 text spills: content matches the standalone corpus-pass store
    doc for doc (including a non-ASCII record through the skip path),
    and read_trec_corpus is never called after pass 1."""
    import tpu_ir.index.docstore as ds
    from tpu_ir.index.streaming import build_index_streaming

    docs = {f"S-{i:03d}": f"salmon run number {i} in the river"
            for i in range(40)}
    docs["S-UNI"] = "café naïve résumé salmon"  # native-scanner skip path
    corpus = tmp_path / "c.trec"
    corpus.write_text("".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in docs.items()))

    out1 = str(tmp_path / "fold")
    # enforce the headline claim, not just content equality: any TREC
    # re-read during the fold build means the store did NOT come from
    # the pass-1 text spills (review r5 — this patch was missing and the
    # test could pass with a silent second corpus pass)
    import tpu_ir.collection.trec as trec_mod

    def _forbid(*a, **k):
        raise AssertionError(
            "corpus re-read: the store fold must use pass-1 text spills")

    with monkeypatch.context() as m:
        m.setattr(trec_mod, "read_trec_corpus", _forbid)
        m.setattr(ds, "read_trec_corpus", _forbid)
        build_index_streaming([str(corpus)], out1, k=1, num_shards=2,
                              batch_docs=16, chargram_ks=[], store=True)
    assert ds.available(out1)

    # the standalone pass over the same corpus must agree per docno
    out2 = str(tmp_path / "twopass")
    build_index_streaming([str(corpus)], out2, k=1, num_shards=2,
                          batch_docs=16, chargram_ks=[])
    ds.build_docstore([str(corpus)], out2)
    s1, s2 = ds.DocStore(out1), ds.DocStore(out2)
    for docno in range(1, len(docs) + 1):
        assert s1.get(docno) == s2.get(docno)
    assert ds.stats(out1)["docs"] == len(docs)


def test_streaming_store_resume_after_pass2_crash(tmp_path, monkeypatch):
    """A crash mid-pass-2 with store=True must resume WITHOUT
    re-tokenizing (text spills survive with the token spills) and still
    assemble a correct store."""
    import pytest

    import tpu_ir.index.streaming as streaming
    from tpu_ir.index.streaming import build_index_streaming

    corpus = write_corpus(tmp_path)
    out = str(tmp_path / "idx")
    real_tok = streaming.make_chunked_tokenizer
    monkeypatch.setattr(  # tiny chunks -> several spill batches
        streaming, "make_chunked_tokenizer",
        lambda paths, k=1, **kw: real_tok(paths, k=k, chunk_bytes=120,
                                          **kw))
    real = streaming.build_postings_packed_jit
    calls = {"n": 0}

    def crashing(*a, **kw):
        calls["n"] += 1
        if calls["n"] > 1:
            raise RuntimeError("injected pass-2 crash")
        return real(*a, **kw)

    monkeypatch.setattr(streaming, "build_postings_packed_jit", crashing)
    with pytest.raises(RuntimeError, match="injected"):
        build_index_streaming([corpus], out, k=1, num_shards=2,
                              batch_docs=2, chargram_ks=[], store=True)

    def boom(*a, **kw):
        raise AssertionError("resume must not re-tokenize")

    monkeypatch.setattr(streaming, "make_chunked_tokenizer", boom)
    monkeypatch.setattr(streaming, "build_postings_packed_jit", real)
    build_index_streaming([corpus], out, k=1, num_shards=2,
                          batch_docs=2, chargram_ks=[], store=True)
    from tpu_ir.index.docstore import DocStore

    store = DocStore(out)
    assert "salmon" in store.get(1)


def test_docstore_consistency_gate(idx):
    """ADVICE r4: a bin/idx size mismatch (crash window between the two
    writes) must fail loudly at load, not decode garbage."""
    import shutil

    import pytest

    from tpu_ir.index.docstore import STORE_BIN, DocStore

    out, _ = idx
    broken = os.path.join(os.path.dirname(out), "broken-idx")
    shutil.copytree(out, broken)
    with open(os.path.join(broken, STORE_BIN), "ab") as f:
        f.write(b"XX")
    with pytest.raises(ValueError, match="inconsistent"):
        DocStore(broken)


def test_cli_snippets_without_store_clean_error(tmp_path, capsys):
    """ADVICE r4: `search --snippets` on a store-less index must exit 1
    with a rebuild hint, not traceback mid-result; `inspect` on a
    docstore.bin missing its idx sidecar must report, not crash."""
    corpus = write_corpus(tmp_path)
    out = str(tmp_path / "idx")
    build_index([corpus], out, k=1, num_shards=2, compute_chargrams=False)
    assert main(["search", out, "--backend", "cpu", "-q", "salmon",
                 "--snippets"]) == 1
    err = capsys.readouterr().err
    assert "--store" in err

    # an orphaned docstore.bin (no idx) inspects cleanly
    with open(os.path.join(out, "docstore.bin"), "wb") as f:
        f.write(b"garbage")
    assert main(["inspect", os.path.join(out, "docstore.bin"),
                 "--backend", "cpu"]) == 0
    assert "unreadable" in capsys.readouterr().out


def test_index_store_rebuilds_inconsistent_store(tmp_path, capsys):
    """`tpu-ir index --store` is the recovery command the DocStore
    consistency error recommends — it must actually rebuild a broken
    (bin/idx mismatched) store, not report its stale stats."""
    from tpu_ir.index import docstore as ds

    corpus = write_corpus(tmp_path)
    out = str(tmp_path / "idx")
    assert main(["index", str(tmp_path), out, "--backend", "cpu",
                 "--shards", "2", "--no-chargrams", "--store"]) == 0
    capsys.readouterr()
    with open(os.path.join(out, "docstore.bin"), "ab") as f:
        f.write(b"XX")
    assert not ds.consistent(out)
    assert main(["index", str(tmp_path), out, "--backend", "cpu",
                 "--shards", "2", "--no-chargrams", "--store"]) == 0
    capsys.readouterr()
    assert ds.consistent(out)
    assert "<DOC" in ds.DocStore(out).get(1)  # loads + decodes cleanly


def test_snippet_full_window_cluster_keeps_last_hit():
    """A matched cluster spanning the whole display window must render
    every matched word — a forced centering shift of 1 used to cut the
    cluster's last word off the window (review r5)."""
    from tpu_ir.analysis.native import make_analyzer
    from tpu_ir.search.snippets import SNIPPET_WORDS, make_snippet

    lead = " ".join(f"pre{i}x" for i in range(10))
    cluster = " ".join(["salmon", "fish"] * (SNIPPET_WORDS // 2))
    doc = f"<DOC><TEXT>{lead} {cluster} tail words here</TEXT></DOC>"
    snip = make_snippet(doc, {"salmon", "fish"}, make_analyzer())
    assert snip.count("**") == 2 * SNIPPET_WORDS  # every cluster word marked
