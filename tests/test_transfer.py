"""shrink_rows_for_fetch padding contract (ADVICE r5): with per-row valid
counts, slots past each row's prefix are ZEROED on device before the
narrowing cast — padding sentinels (PAD_TERM, far outside uint16) must
never wrap into the narrow dtype where a buggy caller could read them as
plausible values."""

import jax.numpy as jnp
import numpy as np

from tpu_ir.ops import PAD_TERM
from tpu_ir.utils.transfer import narrow_uint, shrink_rows_for_fetch


def _padded_rows():
    # 3 shards, capacity 8: valid prefixes 3/5/0, padding = PAD_TERM
    a = np.full((3, 8), PAD_TERM, np.int32)
    a[0, :3] = [7, 8, 9]
    a[1, :5] = [1, 2, 3, 4, 5]
    valid = np.array([3, 5, 0], np.int32)
    return a, valid


def test_valid_rows_zeroes_padding_before_narrow_cast():
    a, valid = _padded_rows()
    out = np.asarray(shrink_rows_for_fetch(
        jnp.asarray(a), 5, dtype=np.uint16, granule=4,
        valid_rows=jnp.asarray(valid)))
    assert out.dtype == np.uint16
    assert out.shape[1] >= 5
    np.testing.assert_array_equal(out[0, :3], [7, 8, 9])
    np.testing.assert_array_equal(out[1, :5], [1, 2, 3, 4, 5])
    # the contract: everything past each row's valid prefix reads 0,
    # not a wrapped PAD_TERM
    assert (out[0, 3:] == 0).all()
    assert (out[2] == 0).all()


def test_legacy_contract_unchanged_without_valid_rows():
    """Without valid counts the old behavior holds: padding wraps under
    the cast and callers must slice each row to its prefix."""
    a, _ = _padded_rows()
    out = np.asarray(shrink_rows_for_fetch(
        jnp.asarray(a), 5, dtype=np.uint16, granule=4))
    assert out.dtype == np.uint16
    np.testing.assert_array_equal(out[1, :5], [1, 2, 3, 4, 5])
    # wrapped sentinel — precisely the hazard valid_rows removes
    assert out[2, 0] == (PAD_TERM & 0xFFFF)


def test_valid_rows_zeroing_applies_even_without_narrowing():
    """When no slice/cast is needed the masked path still zeroes padding,
    so the caller-visible guarantee does not depend on the dtype."""
    a, valid = _padded_rows()
    out = np.asarray(shrink_rows_for_fetch(
        jnp.asarray(a), 8, dtype=np.int32, granule=8,
        valid_rows=jnp.asarray(valid)))
    assert out.dtype == np.int32
    assert (out[0, 3:] == 0).all()
    np.testing.assert_array_equal(out[0, :3], [7, 8, 9])


def test_narrow_uint():
    assert narrow_uint(65535) == np.uint16
    assert narrow_uint(65536) == np.int32
