"""Multi-host distributed SERVING (VERDICT r2 item 2): 2 processes x 2 CPU
devices load one index as a sharded Scorer over the global 4-device mesh —
placement goes through make_array_from_callback per process, queries ride
replicated, results come back replicated — and TF-IDF, BM25 and two-stage
rerank must equal the single-process scorer exactly. The reference's query
engine was a single JVM (IntDocVectorsForwardIndex.java:243-322); this is
the framework's own serve-what-one-host-can't-hold path."""

import json
import os
import socket
import subprocess
import sys

DOCS = {
    "A-1": "alpha bravo charlie alpha delta",
    "A-2": "delta echo foxtrot bravo bravo",
    "B-1": "alpha golf hotel india echo",
    "B-2": "charlie juliet kilo lima bravo",
    "C-1": "echo mike november oscar alpha alpha",
    "C-2": "papa quebec romeo alpha charlie",
    "D-1": "golf hotel juliet kilo mike papa",
    "D-2": "bravo charlie delta echo foxtrot golf",
}

QUERIES = ["alpha", "charlie bravo", "echo golf", "zulu", "alpha delta echo"]

WORKER = r"""
import os, sys, json
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
import jax._src.xla_bridge as xb
for n in list(xb._backend_factories):
    if n != "cpu":
        xb._backend_factories.pop(n, None)

coordinator, pid, index_dir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
queries = json.loads(sys.argv[4])
from tpu_ir.parallel.multihost import init_distributed

init_distributed(coordinator, num_processes=2, process_id=pid)
assert len(jax.devices()) == 4 and len(jax.local_devices()) == 2

from tpu_ir.search import Scorer

scorer = Scorer.load(index_dir, layout="sharded")
assert scorer._mesh.devices.size == 4
out = {}
for scoring in ["tfidf", "bm25"]:
    out[scoring] = [scorer.search_batch(queries, k=5, scoring=scoring)]
out["rerank"] = [scorer.search_batch(queries, k=5, scoring="bm25",
                                     rerank=4)]
print("RESULT " + json.dumps({"pid": pid, "out": out}))
"""


def test_multihost_sharded_serving(tmp_path):
    corpus = tmp_path / "corpus.trec"
    corpus.write_text("".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in DOCS.items()))
    index_dir = str(tmp_path / "idx")

    from tpu_ir.index import build_index
    from tpu_ir.search import Scorer

    build_index([str(corpus)], index_dir, k=1, num_shards=3,
                compute_chargrams=False)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(WORKER)

    env = {**os.environ, "PYTHONPATH": os.getcwd()}
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), f"127.0.0.1:{port}", str(pid),
             index_dir, json.dumps(QUERIES)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            cwd=os.getcwd(), text=True)
        for pid in range(2)
    ]
    results = {}
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, f"worker failed:\n{err[-4000:]}"
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT ")][-1]
        payload = json.loads(line[len("RESULT "):])
        results[payload["pid"]] = payload["out"]

    # both processes saw the same replicated results
    assert results[0] == results[1]

    # and they match the single-process scorer (this process: dense + an
    # 8-virtual-device sharded mesh — layout- and mesh-size-independent)
    want = {}
    ref = Scorer.load(index_dir)
    for scoring in ["tfidf", "bm25"]:
        want[scoring] = [ref.search_batch(QUERIES, k=5, scoring=scoring)]
    want["rerank"] = [ref.search_batch(QUERIES, k=5, scoring="bm25",
                                       rerank=4)]

    got = results[0]
    for key in ["tfidf", "bm25", "rerank"]:
        for got_q, want_q in zip(got[key][0], want[key][0]):
            got_pairs = [(d, round(float(s), 4)) for d, s in got_q]
            want_pairs = [(d, round(float(s), 4)) for d, s in want_q]
            assert got_pairs == want_pairs, (key, got_q, want_q)
