"""Device-op tests: postings build, char-gram build, scoring vs a pure-numpy
oracle that follows the reference reducer/scorer semantics."""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_ir.ops import (
    PAD_TERM,
    build_chargram_index_jit,
    build_postings_jit,
    code_to_gram,
    dense_doc_matrix,
    gram_to_code,
    pack_occurrences,
    pack_term_bytes,
    tfidf_topk_dense,
    tfidf_topk_sparse,
)


def oracle_postings(term_ids, doc_ids):
    """Reference reducer semantics (TermKGramDocIndexer.java:167-213):
    group by (term, doc) summing tf, postings per term sorted tf desc then
    docno asc, df = number of docs."""
    from collections import Counter, defaultdict

    counts = Counter(zip(term_ids, doc_ids))
    by_term = defaultdict(list)
    for (t, d), tf in counts.items():
        by_term[t].append((d, tf))
    out = {}
    for t, posts in by_term.items():
        posts.sort(key=lambda p: (-p[1], p[0]))
        out[t] = posts
    return out


def test_build_postings_matches_oracle():
    rng = np.random.default_rng(0)
    n_tok, vocab, ndocs = 5000, 37, 23
    t = rng.integers(0, vocab, n_tok).astype(np.int32)
    d = rng.integers(1, ndocs + 1, n_tok).astype(np.int32)
    term_ids = np.full(6144, PAD_TERM, np.int32)
    doc_ids = np.zeros(6144, np.int32)
    term_ids[:n_tok] = t
    doc_ids[:n_tok] = d

    p = build_postings_jit(jnp.asarray(term_ids), jnp.asarray(doc_ids),
                           vocab_size=vocab, num_docs=ndocs)
    oracle = oracle_postings(t.tolist(), d.tolist())

    num_pairs = int(p.num_pairs)
    assert num_pairs == sum(len(v) for v in oracle.values())
    indptr = np.asarray(p.indptr)
    pair_doc = np.asarray(p.pair_doc)
    pair_tf = np.asarray(p.pair_tf)
    pair_term = np.asarray(p.pair_term)
    df = np.asarray(p.df)

    for tid in range(vocab):
        lo, hi = indptr[tid], indptr[tid + 1]
        got = list(zip(pair_doc[lo:hi].tolist(), pair_tf[lo:hi].tolist()))
        assert got == oracle.get(tid, []), f"term {tid}"
        assert df[tid] == len(oracle.get(tid, []))
        assert (pair_term[lo:hi] == tid).all()

    # doc lengths
    doc_len = np.asarray(p.doc_len)
    for dn in range(1, ndocs + 1):
        assert doc_len[dn] == int((d == dn).sum())


def test_build_postings_all_padding():
    term_ids = jnp.full((128,), PAD_TERM, jnp.int32)
    doc_ids = jnp.zeros((128,), jnp.int32)
    p = build_postings_jit(term_ids, doc_ids, vocab_size=5, num_docs=3)
    assert int(p.num_pairs) == 0
    assert np.asarray(p.df).sum() == 0


def test_pack_occurrences():
    t, d = pack_occurrences(
        [np.array([3, 1], np.int32), np.array([2], np.int32)],
        np.array([1, 2]), capacity=8)
    assert t.tolist()[:3] == [3, 1, 2]
    assert d.tolist()[:3] == [1, 1, 2]
    assert (t[3:] == PAD_TERM).all()
    with pytest.raises(ValueError):
        pack_occurrences([np.zeros(9, np.int32)], np.array([1]), capacity=8)


def test_round_cap_buckets():
    """Device capacities: >= n, granule-aligned at small sizes, and at
    most 16 distinct buckets per octave at large sizes (each distinct
    capacity is a separate XLA program)."""
    from tpu_ir.ops import round_cap

    for n in (0, 1, 100, 1 << 18, (1 << 18) + 1, 10_600_000, 1 << 30):
        cap = round_cap(n)
        assert cap >= max(n, 1)
        assert cap % (1 << 18) == 0 or cap == 1 << 18
    # one octave at ~16M: every size maps into <= 16 buckets
    caps = {round_cap(n) for n in range(1 << 24, 1 << 25, 1 << 18)}
    assert len(caps) <= 16, sorted(caps)
    # padded waste bounded: granule is 1/16 of the NEXT pow2, so the
    # tail is < n/8 + granule in the worst case (n just above a pow2)
    for n in (10_600_000, 123_456_789, (1 << 24) + 1):
        assert round_cap(n) <= int(n * 1.125) + (1 << 18)


def test_chargram_dispatch_shapes_bucketed(monkeypatch, tmp_path):
    """The chargram device program's input shape must NOT track the
    exact vocab size / longest term: both are corpus-dependent, and an
    exact shape misses the persistent compile cache on every new corpus
    (measured ~100 s of cold compiles at 500k terms vs ~1 s warm).
    Vocabs in the same pow2 bucket share one compiled shape, and the
    padding must not change the artifacts."""
    import tpu_ir.index.builder as builder
    from tpu_ir.index import format as fmt
    from tpu_ir.ops.chargram import build_chargram_index_host

    shapes = []
    orig = builder.build_chargram_index_jit

    def spy(tb, tl, *, k):
        shapes.append(tuple(tb.shape))
        return orig(tb, tl, k=k)

    monkeypatch.setattr(builder, "build_chargram_index_jit", spy)
    terms_a = [f"t{i:05d}" for i in range(900)]
    terms_b = [f"word{i:05d}x" for i in range(700)]
    for name, terms in (("a", terms_a), ("b", terms_b)):
        d = tmp_path / name
        d.mkdir()
        builder.build_chargram_artifacts(str(d), terms, [2])
    assert len(shapes) == 2 and len(set(shapes)) == 1, shapes
    assert shapes[0][0] >= 1024 and shapes[0][0] & (shapes[0][0] - 1) == 0
    # padded rows/columns contribute no windows: artifacts match the
    # unpadded host twin exactly
    z = fmt.load_chargram(str(tmp_path / "b"), 2)
    tb, tl = pack_term_bytes(terms_b, 2)
    hg, hip, hti = build_chargram_index_host(tb, tl, k=2)
    np.testing.assert_array_equal(z["gram_codes"].astype(np.int64),
                                  np.asarray(hg, np.int64))
    np.testing.assert_array_equal(z["indptr"].astype(np.int64),
                                  np.asarray(hip, np.int64))
    np.testing.assert_array_equal(z["term_ids"].astype(np.int64),
                                  np.asarray(hti, np.int64))


def test_chargram_index():
    terms = ["cat", "cart", "dog"]  # ids 0,1,2 assumed pre-sorted? not needed
    k = 2
    tb, tl = pack_term_bytes(terms, k)
    idx = build_chargram_index_jit(jnp.asarray(tb), jnp.asarray(tl), k=k)

    # oracle: $term$ windows
    from collections import defaultdict
    oracle = defaultdict(set)
    for i, term in enumerate(terms):
        padded = f"${term}$"
        for j in range(len(padded) - k + 1):
            oracle[padded[j : j + k]].add(i)

    ng = int(idx.num_grams)
    codes = np.asarray(idx.gram_codes)[:ng]
    indptr = np.asarray(idx.indptr)
    tids = np.asarray(idx.term_ids)
    got = {}
    for g in range(ng):
        gram = code_to_gram(int(codes[g]), k)
        got[gram] = sorted(tids[indptr[g] : indptr[g + 1]].tolist())
    assert got == {g: sorted(v) for g, v in oracle.items()}
    # per-gram term lists are sorted (reference merge keeps lists sorted)
    for g in range(ng):
        seg = tids[indptr[g] : indptr[g + 1]].tolist()
        assert seg == sorted(seg)
    assert (np.diff(codes) > 0).all()  # grams sorted unique
    # round-trip helper
    assert gram_to_code(code_to_gram(int(codes[0]), k), k) == int(codes[0])


def oracle_tfidf(postings_by_term, query_tids, n_docs, k=10):
    """Reference rank() semantics (IntDocVectorsForwardIndex.java:192-223),
    with float idf (the int-division quirk is tested separately)."""
    scores = {}
    for tid in query_tids:
        posts = postings_by_term.get(tid, [])
        dfv = len(posts)
        if dfv == 0:
            continue
        idf = np.log10(n_docs / dfv)
        for d, tf in posts:
            scores[d] = scores.get(d, 0.0) + (1 + np.log(tf)) * idf
    # engine semantics: zero-score docs (idf==0) are not returned
    ranked = sorted(
        ((d, s) for d, s in scores.items() if s > 0),
        key=lambda kv: (-kv[1], kv[0]))[:k]
    return ranked


def _small_index():
    rng = np.random.default_rng(1)
    n_tok, vocab, ndocs = 1500, 200, 17
    t = rng.integers(0, vocab, n_tok).astype(np.int32)
    d = rng.integers(1, ndocs + 1, n_tok).astype(np.int32)
    term_ids = np.full(4096, PAD_TERM, np.int32)
    doc_ids = np.zeros(4096, np.int32)
    term_ids[:n_tok] = t
    doc_ids[:n_tok] = d
    p = build_postings_jit(jnp.asarray(term_ids), jnp.asarray(doc_ids),
                           vocab_size=vocab, num_docs=ndocs)
    oracle = oracle_postings(t.tolist(), d.tolist())
    return p, oracle, vocab, ndocs


def test_tfidf_dense_matches_oracle():
    p, oracle, vocab, ndocs = _small_index()
    mat = dense_doc_matrix(p.pair_term, p.pair_doc, p.pair_tf,
                           vocab_size=vocab, num_docs=ndocs)
    queries = np.array([[0, 5], [3, -1], [28, 2], [7, 7]], np.int32)
    scores, docnos = tfidf_topk_dense(
        jnp.asarray(queries), mat, p.df, jnp.int32(ndocs), k=5)
    scores, docnos = np.asarray(scores), np.asarray(docnos)
    for qi, q in enumerate(queries):
        tids = [x for x in q.tolist() if x >= 0]
        want = oracle_tfidf(oracle, tids, ndocs, k=5)
        got = [(int(dn), float(s)) for s, dn in zip(scores[qi], docnos[qi]) if dn > 0]
        assert len(got) == len(want)
        for (gd, gs), (wd, ws) in zip(got, want):
            assert gs == pytest.approx(ws, rel=1e-4)
        # same doc set at equal scores (tie order may differ)
        assert {g[0] for g in got} == {w[0] for w in want}


def test_tfidf_sparse_matches_dense():
    p, oracle, vocab, ndocs = _small_index()
    mat = dense_doc_matrix(p.pair_term, p.pair_doc, p.pair_tf,
                           vocab_size=vocab, num_docs=ndocs)
    # build padded per-term postings from CSR
    indptr = np.asarray(p.indptr)
    pcap = int(np.max(np.diff(indptr)))
    post_docs = np.zeros((vocab, pcap), np.int32)
    post_tfs = np.zeros((vocab, pcap), np.int32)
    pd, pt = np.asarray(p.pair_doc), np.asarray(p.pair_tf)
    for tid in range(vocab):
        lo, hi = indptr[tid], indptr[tid + 1]
        post_docs[tid, : hi - lo] = pd[lo:hi]
        post_tfs[tid, : hi - lo] = pt[lo:hi]

    queries = np.array([[0, 5], [3, -1], [11, 2]], np.int32)
    s1, d1 = tfidf_topk_dense(jnp.asarray(queries), mat, p.df,
                              jnp.int32(ndocs), k=5)
    s2, d2 = tfidf_topk_sparse(jnp.asarray(queries), jnp.asarray(post_docs),
                               jnp.asarray(post_tfs), p.df, jnp.int32(ndocs),
                               num_docs=ndocs, k=5)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-4)
    # doc sets match per rank where scores are distinct
    assert (np.asarray(d1) == np.asarray(d2)).mean() > 0.9


def test_compat_int_idf():
    p, oracle, vocab, ndocs = _small_index()
    mat = dense_doc_matrix(p.pair_term, p.pair_doc, p.pair_tf,
                           vocab_size=vocab, num_docs=ndocs)
    # term 2: df=2, so the Java int division gives ndocs//df = 8 and a
    # POSITIVE idf (the old choice, term 4 with df=9, had 17//9 = 1 ->
    # idf exactly 0, every score 0.0, and the comparison loop compared
    # nothing — review r5)
    tid = 2
    dfv = int(np.asarray(p.df)[tid])
    assert ndocs // dfv >= 2, "fixture drift: pick a term with idf > 0"
    q = np.array([[tid, -1]], np.int32)
    s, dn = tfidf_topk_dense(jnp.asarray(q), mat, p.df, jnp.int32(ndocs),
                             k=3, compat_int_idf=True)
    posts = oracle.get(tid, [])
    want = [pair for pair in sorted(
        ((1 + np.log(tf)) * np.log10(ndocs // dfv), d)
        for d, tf in posts)[::-1][:3] if pair[0] > 0]
    got = [float(x) for x in np.asarray(s)[0] if x > 0]
    assert want and len(got) == len(want)  # zip would silently truncate
    for g, (w, _) in zip(got, want):
        assert g == pytest.approx(w, rel=1e-4)


def _tier_regimes(vocab, ndocs):
    """Layout parameter sets spanning: everything-hot, hot-strip starved by
    the budget (forces multi-tier cold coverage of high-df terms), and
    single-tier-dominant (large base cap)."""
    return [
        dict(hot_budget=10**12, base_cap=2, growth=4),   # p99 split, roomy
        dict(hot_budget=1, base_cap=2, growth=2),        # 1 hot row max
        dict(hot_budget=(ndocs + 1) * 2, base_cap=1, growth=4),  # 2 hot rows
        dict(hot_budget=1, base_cap=4096, growth=4),     # one big tier
    ]


def test_tfidf_tiered_matches_dense():
    """The tiered sparse layout must equal the dense path under every
    hot-budget / tier-capacity regime."""
    from tpu_ir.ops.scoring import tfidf_topk_tiered
    from tpu_ir.search.layout import build_tiered_layout

    p, oracle, vocab, ndocs = _small_index()
    mat = dense_doc_matrix(p.pair_term, p.pair_doc, p.pair_tf,
                           vocab_size=vocab, num_docs=ndocs)
    df = np.asarray(p.df)
    pd_, pt_ = np.asarray(p.pair_doc), np.asarray(p.pair_tf)

    queries = np.array([[0, 5, 199], [3, -1, -1], [11, 2, 7]], np.int32)
    s1, d1 = tfidf_topk_dense(jnp.asarray(queries), mat, p.df,
                              jnp.int32(ndocs), k=5)
    for kw in _tier_regimes(vocab, ndocs):
        t = build_tiered_layout(pd_, pt_, df, num_docs=ndocs, **kw)
        s2, d2 = tfidf_topk_tiered(
            jnp.asarray(queries), jnp.asarray(t.hot_rank),
            t.hot_device(), jnp.asarray(t.tier_of),
            jnp.asarray(t.row_of),
            tuple(jnp.asarray(a) for a in t.tier_docs),
            tuple(jnp.asarray(a) for a in t.tier_tfs),
            p.df, jnp.int32(ndocs), num_docs=ndocs, k=5)
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                                   rtol=1e-4, err_msg=str(kw))


def test_bm25_tiered_matches_dense():
    """BM25 on the tiered layout must equal bm25_topk_dense under every
    layout regime (the path that unlocks BM25 past the dense budget)."""
    from tpu_ir.ops.scoring import (bm25_topk_dense, bm25_topk_tiered,
                                    dense_tf_matrix)
    from tpu_ir.search.layout import build_tiered_layout

    p, oracle, vocab, ndocs = _small_index()
    tf_mat = dense_tf_matrix(p.pair_term, p.pair_doc, p.pair_tf,
                             vocab_size=vocab, num_docs=ndocs)
    df = np.asarray(p.df)
    pd_, pt_ = np.asarray(p.pair_doc), np.asarray(p.pair_tf)
    rng = np.random.default_rng(7)
    doc_len = np.zeros(ndocs + 1, np.int32)
    doc_len[1:] = rng.integers(5, 50, ndocs)

    queries = np.array([[0, 5, 199], [3, -1, -1], [11, 2, 7]], np.int32)
    s1, d1 = bm25_topk_dense(jnp.asarray(queries), tf_mat, p.df,
                             jnp.asarray(doc_len), jnp.int32(ndocs), k=5)
    for kw in _tier_regimes(vocab, ndocs):
        t = build_tiered_layout(pd_, pt_, df, num_docs=ndocs, **kw)
        s2, d2 = bm25_topk_tiered(
            jnp.asarray(queries), jnp.asarray(t.hot_rank),
            t.hot_device(), jnp.asarray(t.tier_of),
            jnp.asarray(t.row_of),
            tuple(jnp.asarray(a) for a in t.tier_docs),
            tuple(jnp.asarray(a) for a in t.tier_tfs),
            p.df, jnp.asarray(doc_len), jnp.int32(ndocs),
            num_docs=ndocs, k=5)
        # scores only: ulp-level accumulation-order differences between the
        # einsum and per-tier scatter paths may reorder tied docnos
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                                   rtol=1e-4, err_msg=str(kw))


def test_hot_only_scores_exactly_the_hot_strip():
    """hot_only=True (the overload ladder's cheapest device level) must
    score EXACTLY the hot-strip contributions: a mixed hot+cold query
    under hot_only equals the same query with its cold terms removed
    under full scoring, and a cold-only query scores nothing."""
    from tpu_ir.ops.scoring import tfidf_topk_tiered
    from tpu_ir.search.layout import build_tiered_layout

    p, oracle, vocab, ndocs = _small_index()
    df = np.asarray(p.df)
    pd_, pt_ = np.asarray(p.pair_doc), np.asarray(p.pair_tf)
    t = build_tiered_layout(pd_, pt_, df, num_docs=ndocs,
                            hot_budget=10**12, base_cap=2, growth=4)
    hot = np.nonzero(t.hot_rank >= 0)[0]
    cold = np.nonzero((t.hot_rank < 0) & (df > 0))[0]
    assert len(hot) >= 1 and len(cold) >= 1, "regime must split the vocab"
    args = (jnp.asarray(t.hot_rank), t.hot_device(),
            jnp.asarray(t.tier_of), jnp.asarray(t.row_of),
            tuple(jnp.asarray(a) for a in t.tier_docs),
            tuple(jnp.asarray(a) for a in t.tier_tfs),
            p.df, jnp.int32(ndocs))

    q_mixed = np.array([[int(hot[0]), int(cold[0])]], np.int32)
    q_hot = np.array([[int(hot[0]), -1]], np.int32)
    s_ho, d_ho = tfidf_topk_tiered(jnp.asarray(q_mixed), *args,
                                   num_docs=ndocs, k=5, hot_only=True)
    s_ref, d_ref = tfidf_topk_tiered(jnp.asarray(q_hot), *args,
                                     num_docs=ndocs, k=5)
    np.testing.assert_allclose(np.asarray(s_ho), np.asarray(s_ref),
                               rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(d_ho), np.asarray(d_ref))

    q_cold = np.array([[int(cold[0]), -1]], np.int32)
    s0, d0 = tfidf_topk_tiered(jnp.asarray(q_cold), *args,
                               num_docs=ndocs, k=5, hot_only=True)
    assert not np.asarray(d0).any(), "cold-only query must score nothing"

    # skip_hot + hot_only together would score nothing at all — rejected
    with pytest.raises(ValueError):
        tfidf_topk_tiered(jnp.asarray(q_hot), *args, num_docs=ndocs,
                          k=5, hot_only=True, skip_hot=True)


def test_hot_strip_coo_densify():
    """The hot strip is carried as COO postings (the serving cold-start
    fix: COO crosses the H2D link, the dense strip is scattered on device).
    hot_device() must equal the host densification, every hot term's full
    postings list must land in its strip row, and the COO columns must be
    slim (uint16) when the corpus allows."""
    from tpu_ir.search.layout import build_tiered_layout

    p, oracle, vocab, ndocs = _small_index()
    df = np.asarray(p.df)
    pd_, pt_ = np.asarray(p.pair_doc), np.asarray(p.pair_tf)
    for kw in _tier_regimes(vocab, ndocs):
        t = build_tiered_layout(pd_, pt_, df, num_docs=ndocs, **kw)
        dense = t.hot_dense()
        assert dense.shape == (t.num_hot, ndocs + 1)
        np.testing.assert_array_equal(np.asarray(t.hot_device()), dense)
        # every hot term's raw tfs, straight from the CSR columns
        indptr = np.concatenate([[0], np.cumsum(df, dtype=np.int64)])
        for tid in np.nonzero(t.hot_rank >= 0)[0]:
            row = dense[t.hot_rank[tid]]
            sl = slice(indptr[tid], indptr[tid + 1])
            np.testing.assert_array_equal(row[pd_[sl]], pt_[sl])
            assert np.count_nonzero(row) == df[tid]
    # this corpus is small: every column must have taken the uint16 path
    t = build_tiered_layout(pd_, pt_, df, num_docs=ndocs, hot_budget=10**12)
    assert (t.hot_rows.dtype == t.hot_docs.dtype == t.hot_vals.dtype
            == np.uint16)


def test_tiered_ignores_df0_and_out_of_range_terms():
    """Regression: a df=0 vocab term must contribute nothing under tiered
    BM25 (its idf is nonzero, and an unmasked tier_of=0 default would alias
    it onto tier 0 row 0's postings); ditto ids past the vocabulary."""
    from tpu_ir.ops.scoring import (bm25_topk_dense, bm25_topk_tiered,
                                    dense_tf_matrix, tfidf_topk_tiered)
    from tpu_ir.search.layout import build_tiered_layout

    rng = np.random.default_rng(3)
    vocab, ndocs = 210, 17  # ids 200..209 never occur -> df = 0
    t = rng.integers(0, 200, 1500).astype(np.int32)
    d = rng.integers(1, ndocs + 1, 1500).astype(np.int32)
    term_ids = np.full(4096, PAD_TERM, np.int32)
    doc_ids = np.zeros(4096, np.int32)
    term_ids[:1500] = t
    doc_ids[:1500] = d
    p = build_postings_jit(jnp.asarray(term_ids), jnp.asarray(doc_ids),
                           vocab_size=vocab, num_docs=ndocs)
    df = np.asarray(p.df)
    assert df[205] == 0
    lay = build_tiered_layout(np.asarray(p.pair_doc), np.asarray(p.pair_tf),
                              df, num_docs=ndocs)
    args = (jnp.asarray(lay.hot_rank), lay.hot_device(),
            jnp.asarray(lay.tier_of), jnp.asarray(lay.row_of),
            tuple(jnp.asarray(a) for a in lay.tier_docs),
            tuple(jnp.asarray(a) for a in lay.tier_tfs))
    doc_len = np.zeros(ndocs + 1, np.int32)
    doc_len[1:] = rng.integers(5, 50, ndocs)

    queries = jnp.asarray(np.array([[205, -1], [300, -1]], np.int32))
    s, dn = bm25_topk_tiered(queries, *args, p.df, jnp.asarray(doc_len),
                             jnp.int32(ndocs), num_docs=ndocs, k=5)
    assert (np.asarray(s) == 0).all() and (np.asarray(dn) == 0).all()
    s, dn = tfidf_topk_tiered(queries, *args, p.df, jnp.int32(ndocs),
                              num_docs=ndocs, k=5)
    assert (np.asarray(s) == 0).all() and (np.asarray(dn) == 0).all()

    tf_mat = dense_tf_matrix(p.pair_term, p.pair_doc, p.pair_tf,
                             vocab_size=vocab, num_docs=ndocs)
    s, dn = bm25_topk_dense(queries, tf_mat, p.df, jnp.asarray(doc_len),
                            jnp.int32(ndocs), k=5)
    assert (np.asarray(s) == 0).all() and (np.asarray(dn) == 0).all()


def test_build_postings_packed_matches_unpacked():
    """The slim-upload front end (uint16 term ids + on-device doc-column
    reconstruction from (docno, length)) must agree with build_postings."""
    from tpu_ir.ops import PAD_TERM_U16, build_postings_packed_jit

    rng = np.random.default_rng(3)
    vocab, ndocs, cap = 37, 23, 4096
    lengths = rng.integers(0, 40, ndocs).astype(np.int32)  # incl zero-len doc
    docnos = rng.permutation(ndocs).astype(np.int32) + 1
    n_tok = int(lengths.sum())
    t = rng.integers(0, vocab, n_tok).astype(np.int32)
    d = np.repeat(docnos, lengths)

    ref_t = np.full(cap, PAD_TERM, np.int32)
    ref_d = np.zeros(cap, np.int32)
    ref_t[:n_tok] = t
    ref_d[:n_tok] = d
    ref = build_postings_jit(jnp.asarray(ref_t), jnp.asarray(ref_d),
                             vocab_size=vocab, num_docs=ndocs)

    for use16 in (True, False):
        packed = np.full(cap, PAD_TERM_U16 if use16 else PAD_TERM,
                         np.uint16 if use16 else np.int32)
        packed[:n_tok] = t
        got = build_postings_packed_jit(
            jnp.asarray(packed), jnp.asarray(docnos), jnp.asarray(lengths),
            vocab_size=vocab, num_docs=ndocs)
        assert int(got.num_pairs) == int(ref.num_pairs)
        np.testing.assert_array_equal(np.asarray(got.df), np.asarray(ref.df))
        np.testing.assert_array_equal(np.asarray(got.doc_len),
                                      np.asarray(ref.doc_len))
        n = int(ref.num_pairs)
        for name in ("pair_term", "pair_doc", "pair_tf"):
            np.testing.assert_array_equal(
                np.asarray(getattr(got, name))[:n],
                np.asarray(getattr(ref, name))[:n], err_msg=name)


def test_build_postings_packed_u16_boundary_ids():
    """Term ids right at the uint16 edge (65533/65534) survive the 0xFFFF
    sentinel remap; the sentinel itself is reserved for padding."""
    from tpu_ir.ops import PAD_TERM_U16, build_postings_packed_jit

    vocab = 65535 - 1  # the builder's use16 cutoff: v < 65535
    packed = np.full(256, PAD_TERM_U16, np.uint16)
    packed[:3] = [65533, 0, 65533]
    docnos = np.array([7, 9], np.int32)
    lengths = np.array([2, 1], np.int32)
    p = build_postings_packed_jit(jnp.asarray(packed), jnp.asarray(docnos),
                                  jnp.asarray(lengths),
                                  vocab_size=vocab, num_docs=9)
    assert int(p.num_pairs) == 3
    df = np.asarray(p.df)
    assert df[65533] == 2 and df[0] == 1 and df.sum() == 3


def test_narrow_uint_boundary():
    from tpu_ir.utils.transfer import narrow_uint

    assert narrow_uint(0) == np.uint16
    assert narrow_uint(65535) == np.uint16   # exact fit
    assert narrow_uint(65536) == np.int32
    assert np.array(65535, narrow_uint(65535)) == 65535  # no wraparound


def test_shrink_for_fetch_and_pairs():
    from tpu_ir.utils.transfer import shrink_for_fetch, shrink_pairs

    a = jnp.arange(1 << 16, dtype=jnp.int32)
    out = shrink_for_fetch(a, 100, dtype=np.uint16, granule=64)
    assert out.shape[0] == 128 and out.dtype == np.uint16
    np.testing.assert_array_equal(np.asarray(out)[:100], np.arange(100))
    # no-op path returns the same array
    assert shrink_for_fetch(a, 1 << 16, granule=64) is a

    pd = jnp.full((1 << 10,), 70000, jnp.int32)
    ptf = jnp.full((1 << 10,), 3, jnp.int32)
    spd, stf = shrink_pairs(pd, ptf, 10, num_docs=100_000, tf_max=3,
                            granule=32)
    assert spd.dtype == np.int32     # docnos don't fit uint16
    assert stf.dtype == np.uint16
    assert int(np.asarray(spd)[0]) == 70000


def test_tiered_big_tier_cond_path():
    """Terms in tiers with cap >= 4096 (the lax.cond-gated stages) must
    score identically to the dense path — including blocks where no query
    term lands in the big tier (the skip branch)."""
    from tpu_ir.ops.scoring import tfidf_topk_tiered
    from tpu_ir.search.layout import build_tiered_layout

    rng = np.random.default_rng(9)
    ndocs, vocab = 9000, 50
    # term 0: df 5000 -> tier cap 8192 (cond-gated); term 1: df 6000 but
    # hot (hot strip takes the top-df terms); the rest small
    dfs = [5000, 6000] + [int(x) for x in rng.integers(1, 50, vocab - 2)]
    pt, pd, ptf = [], [], []
    for tid, df_t in enumerate(dfs):
        docs = rng.choice(ndocs, df_t, replace=False) + 1
        tfs = rng.integers(1, 9, df_t)
        order = np.lexsort((docs, -tfs))
        pt.extend([tid] * df_t)
        pd.extend(docs[order].tolist())
        ptf.extend(tfs[order].tolist())
    pt = np.array(pt, np.int32)
    pd = np.array(pd, np.int32)
    ptf = np.array(ptf, np.int32)
    df = np.bincount(pt, minlength=vocab).astype(np.int32)

    tiers = build_tiered_layout(pd, ptf, df, num_docs=ndocs,
                                hot_budget=2 * (ndocs + 1))  # 2 hot rows
    assert max(a.shape[1] for a in tiers.tier_docs) >= 4096

    mat = dense_doc_matrix(jnp.asarray(pt), jnp.asarray(pd),
                           jnp.asarray(ptf), vocab_size=vocab,
                           num_docs=ndocs)
    # queries hitting the big tier, the hot strip, small tiers, and one
    # block-wide big-tier miss (terms 2.. only)
    qs = np.array([[0, 5], [1, 7], [3, 9], [2, 4]], np.int32)
    for q in (qs, qs[2:]):  # second batch: nothing in the big tier
        s1, d1 = tfidf_topk_dense(jnp.asarray(q), mat, jnp.asarray(df),
                                  jnp.int32(ndocs), k=10)
        s2, d2 = tfidf_topk_tiered(
            jnp.asarray(q), jnp.asarray(tiers.hot_rank),
            tiers.hot_device(), jnp.asarray(tiers.tier_of),
            jnp.asarray(tiers.row_of),
            tuple(jnp.asarray(a) for a in tiers.tier_docs),
            tuple(jnp.asarray(a) for a in tiers.tier_tfs),
            jnp.asarray(df), jnp.int32(ndocs), num_docs=ndocs, k=10)
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                                   rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))

class TestChargramHostFallback:
    """3 < k <= 7 grams pack into int64 on host (ops/chargram.py); the
    semantics must match the device path's: '$term$' byte windows, per-gram
    sorted-unique term lists."""

    def test_matches_python_oracle(self):
        from tpu_ir.ops.chargram import (
            build_chargram_index_host, gram_to_code, pack_term_bytes)

        terms = sorted(["alpha", "alphabet", "beta", "albania", "a"])
        tb, tl = pack_term_bytes(terms, 5)
        codes, indptr, tids = build_chargram_index_host(tb, tl, k=5)

        oracle: dict[bytes, set] = {}
        for i, t in enumerate(terms):
            s = b"$" + t.encode() + b"$"
            for j in range(len(s) - 4):
                oracle.setdefault(s[j : j + 5], set()).add(i)
        assert len(codes) == len(oracle)
        for gram, want in oracle.items():
            gi = int(np.searchsorted(codes, gram_to_code(gram, 5)))
            got = tids[indptr[gi] : indptr[gi + 1]].tolist()
            assert got == sorted(want), gram

    def test_k4_non_ascii_routed_to_host_path(self, tmp_path):
        """k=4 would shift a gram's leading byte by 24 bits in int32 —
        negative codes for any non-ASCII byte >= 0x80, unfindable by
        gram_to_code's unsigned lookup. The builder must route k=4 to the
        int64 host twin and wildcard expansion over it must still match
        multi-byte UTF-8 terms end-to-end."""
        from tpu_ir.index import build_index
        from tpu_ir.index import format as fmt
        from tpu_ir.search.wildcard import WildcardLookup

        corpus = tmp_path / "c.trec"
        corpus.write_text(
            "<DOC>\n<DOCNO> U-1 </DOCNO>\n<TEXT>\ncafézzz naïveté plain"
            "\n</TEXT>\n</DOC>\n", encoding="utf-8")
        idx = str(tmp_path / "idx")
        meta = build_index([str(corpus)], idx, chargram_ks=[4],
                           num_shards=2)
        assert meta.chargram_ks == [4]
        z = fmt.load_chargram(idx, 4)
        assert (np.asarray(z["gram_codes"]) >= 0).all()
        lookup = WildcardLookup.load(idx, 4)
        assert "cafézzz" in lookup.expand("café*")
        # and the device program refuses k=4 outright
        tb, tl = pack_term_bytes(["café"], 4)
        with pytest.raises(ValueError):
            build_chargram_index_jit(jnp.asarray(tb), jnp.asarray(tl), k=4)

    def test_k_gt_7_rejected(self):
        """k=8 would let grams with a >=0x80 leading byte (any non-ASCII)
        overflow int64's sign bit and silently break lookups."""
        from tpu_ir.ops.chargram import (
            build_chargram_index_host, pack_term_bytes)

        tb, tl = pack_term_bytes(["word"], 8)
        with pytest.raises(ValueError):
            build_chargram_index_host(tb, tl, k=8)

    def test_non_ascii_grams_roundtrip(self):
        """Multi-byte UTF-8 grams (leading byte >= 0x80) must stay
        positive and matchable at the max host k."""
        from tpu_ir.ops.chargram import (
            build_chargram_index_host, gram_to_code, pack_term_bytes)

        terms = sorted(["caféterm", "naïveword"])
        tb, tl = pack_term_bytes(terms, 7)
        codes, indptr, tids = build_chargram_index_host(tb, tl, k=7)
        assert (codes >= 0).all()
        s = b"$" + terms[0].encode("utf-8") + b"$"
        gram = s[1:8]  # window containing the 2-byte é sequence
        gi = int(np.searchsorted(codes, gram_to_code(gram, 7)))
        assert codes[gi] == gram_to_code(gram, 7)
        assert 0 in tids[indptr[gi] : indptr[gi + 1]]

    def test_builder_integration_and_expand(self, tmp_path):
        """chargram_ks mixing device (<=3) and host (>3) ks builds both
        artifacts, and wildcard expansion works over the k=5 index."""
        from tpu_ir.index import build_index
        from tpu_ir.search.wildcard import WildcardLookup

        corpus = tmp_path / "c.trec"
        corpus.write_text(
            "<DOC>\n<DOCNO> W-1 </DOCNO>\n<TEXT>\nfishing fisher walked"
            "\n</TEXT>\n</DOC>\n")
        idx = str(tmp_path / "idx")
        meta = build_index([str(corpus)], idx, chargram_ks=[2, 5],
                           num_shards=2)
        assert meta.chargram_ks == [2, 5]
        lookup = WildcardLookup.load(idx, 5)
        got = lookup.expand("fish*")
        assert "fisher" in got and "fish" in got  # 'fishing' stems to fish


def test_sparse_drops_out_of_range_term_ids():
    """tfidf_topk_sparse must ignore query ids >= V like its siblings —
    an unmasked id would clamp its gathers to the LAST vocabulary term
    and silently score its postings (review r5)."""
    p, oracle, vocab, ndocs = _small_index()
    indptr = np.asarray(p.indptr)
    pcap = int(np.max(np.diff(indptr)))
    post_docs = np.zeros((vocab, pcap), np.int32)
    post_tfs = np.zeros((vocab, pcap), np.int32)
    pd, pt = np.asarray(p.pair_doc), np.asarray(p.pair_tf)
    for tid in range(vocab):
        lo, hi = indptr[tid], indptr[tid + 1]
        post_docs[tid, : hi - lo] = pd[lo:hi]
        post_tfs[tid, : hi - lo] = pt[lo:hi]
    q_ok = np.array([[0, 5, -1]], np.int32)
    q_oob = np.array([[0, 5, vocab]], np.int32)  # vocab == out of range
    s1, d1 = tfidf_topk_sparse(jnp.asarray(q_ok), jnp.asarray(post_docs),
                               jnp.asarray(post_tfs), p.df,
                               jnp.int32(ndocs), num_docs=ndocs, k=5)
    s2, d2 = tfidf_topk_sparse(jnp.asarray(q_oob), jnp.asarray(post_docs),
                               jnp.asarray(post_tfs), p.df,
                               jnp.int32(ndocs), num_docs=ndocs, k=5)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))


def test_bm25_b1_empty_doc_no_nan():
    """At b=1.0 an empty doc has dl_norm 0, and an unguarded saturation
    divides 0/0 — the NaN outranks every real score in lax.top_k and
    burns top-k slots (review r5: verified scores like [0., ...] with the
    best real doc dropped). The guarded curve must rank real docs only."""
    from tpu_ir.ops import bm25_topk_dense
    from tpu_ir.ops.scoring import dense_tf_matrix

    # docs 1..2 real, doc 3 EMPTY (no postings, doc_len 0)
    pair_term = jnp.asarray(np.array([0, 0, 1], np.int32))
    pair_doc = jnp.asarray(np.array([1, 2, 1], np.int32))
    pair_tf = jnp.asarray(np.array([2, 1, 1], np.int32))
    tf_mat = dense_tf_matrix(pair_term, pair_doc, pair_tf,
                             vocab_size=2, num_docs=3)
    df = jnp.asarray(np.array([2, 1], np.int32))
    doc_len = jnp.asarray(np.array([0, 3, 1, 0], np.int32))
    q = jnp.asarray(np.array([[0, 1]], np.int32))
    s, d = bm25_topk_dense(q, tf_mat, df, doc_len, jnp.int32(3),
                           k=3, b=1.0)
    s, d = np.asarray(s), np.asarray(d)
    assert np.isfinite(s).all()
    assert d[0, 0] == 1 and s[0, 0] > 0     # best real doc leads
    assert 3 not in d[0]                     # the empty doc never ranks


def test_reduce_weighted_postings_empty_input():
    """A zero-length bucket must return num_pairs 0, not IndexError —
    the guard build_postings always had (review r5)."""
    from tpu_ir.ops.postings import reduce_weighted_postings

    t = jnp.zeros((0,), jnp.int32)
    out = reduce_weighted_postings(t, t, t, vocab_size=5)
    assert int(out[4]) == 0
    assert np.asarray(out[3]).sum() == 0  # df all zero


def test_pack_occurrences_length_mismatch_is_loud():
    """zip truncation used to silently drop whole documents' postings
    when docnos was shorter than the per-doc id lists (review r5)."""
    with pytest.raises(ValueError):
        pack_occurrences(
            [np.zeros(2, np.int32), np.ones(2, np.int32),
             np.full(2, 2, np.int32)],
            np.array([1, 2]), capacity=8)
