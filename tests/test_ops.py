"""Device-op tests: postings build, char-gram build, scoring vs a pure-numpy
oracle that follows the reference reducer/scorer semantics."""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_ir.ops import (
    PAD_TERM,
    build_chargram_index_jit,
    build_postings_jit,
    code_to_gram,
    dense_doc_matrix,
    gram_to_code,
    pack_occurrences,
    pack_term_bytes,
    tfidf_topk_dense,
    tfidf_topk_sparse,
)


def oracle_postings(term_ids, doc_ids):
    """Reference reducer semantics (TermKGramDocIndexer.java:167-213):
    group by (term, doc) summing tf, postings per term sorted tf desc then
    docno asc, df = number of docs."""
    from collections import Counter, defaultdict

    counts = Counter(zip(term_ids, doc_ids))
    by_term = defaultdict(list)
    for (t, d), tf in counts.items():
        by_term[t].append((d, tf))
    out = {}
    for t, posts in by_term.items():
        posts.sort(key=lambda p: (-p[1], p[0]))
        out[t] = posts
    return out


def test_build_postings_matches_oracle():
    rng = np.random.default_rng(0)
    n_tok, vocab, ndocs = 5000, 37, 23
    t = rng.integers(0, vocab, n_tok).astype(np.int32)
    d = rng.integers(1, ndocs + 1, n_tok).astype(np.int32)
    term_ids = np.full(6144, PAD_TERM, np.int32)
    doc_ids = np.zeros(6144, np.int32)
    term_ids[:n_tok] = t
    doc_ids[:n_tok] = d

    p = build_postings_jit(jnp.asarray(term_ids), jnp.asarray(doc_ids),
                           vocab_size=vocab, num_docs=ndocs)
    oracle = oracle_postings(t.tolist(), d.tolist())

    num_pairs = int(p.num_pairs)
    assert num_pairs == sum(len(v) for v in oracle.values())
    indptr = np.asarray(p.indptr)
    pair_doc = np.asarray(p.pair_doc)
    pair_tf = np.asarray(p.pair_tf)
    pair_term = np.asarray(p.pair_term)
    df = np.asarray(p.df)

    for tid in range(vocab):
        lo, hi = indptr[tid], indptr[tid + 1]
        got = list(zip(pair_doc[lo:hi].tolist(), pair_tf[lo:hi].tolist()))
        assert got == oracle.get(tid, []), f"term {tid}"
        assert df[tid] == len(oracle.get(tid, []))
        assert (pair_term[lo:hi] == tid).all()

    # doc lengths
    doc_len = np.asarray(p.doc_len)
    for dn in range(1, ndocs + 1):
        assert doc_len[dn] == int((d == dn).sum())


def test_build_postings_all_padding():
    term_ids = jnp.full((128,), PAD_TERM, jnp.int32)
    doc_ids = jnp.zeros((128,), jnp.int32)
    p = build_postings_jit(term_ids, doc_ids, vocab_size=5, num_docs=3)
    assert int(p.num_pairs) == 0
    assert np.asarray(p.df).sum() == 0


def test_pack_occurrences():
    t, d = pack_occurrences(
        [np.array([3, 1], np.int32), np.array([2], np.int32)],
        np.array([1, 2]), capacity=8)
    assert t.tolist()[:3] == [3, 1, 2]
    assert d.tolist()[:3] == [1, 1, 2]
    assert (t[3:] == PAD_TERM).all()
    with pytest.raises(ValueError):
        pack_occurrences([np.zeros(9, np.int32)], np.array([1]), capacity=8)


def test_chargram_index():
    terms = ["cat", "cart", "dog"]  # ids 0,1,2 assumed pre-sorted? not needed
    k = 2
    tb, tl = pack_term_bytes(terms, k)
    idx = build_chargram_index_jit(jnp.asarray(tb), jnp.asarray(tl), k=k)

    # oracle: $term$ windows
    from collections import defaultdict
    oracle = defaultdict(set)
    for i, term in enumerate(terms):
        padded = f"${term}$"
        for j in range(len(padded) - k + 1):
            oracle[padded[j : j + k]].add(i)

    ng = int(idx.num_grams)
    codes = np.asarray(idx.gram_codes)[:ng]
    indptr = np.asarray(idx.indptr)
    tids = np.asarray(idx.term_ids)
    got = {}
    for g in range(ng):
        gram = code_to_gram(int(codes[g]), k)
        got[gram] = sorted(tids[indptr[g] : indptr[g + 1]].tolist())
    assert got == {g: sorted(v) for g, v in oracle.items()}
    # per-gram term lists are sorted (reference merge keeps lists sorted)
    for g in range(ng):
        seg = tids[indptr[g] : indptr[g + 1]].tolist()
        assert seg == sorted(seg)
    assert (np.diff(codes) > 0).all()  # grams sorted unique
    # round-trip helper
    assert gram_to_code(code_to_gram(int(codes[0]), k), k) == int(codes[0])


def oracle_tfidf(postings_by_term, query_tids, n_docs, k=10):
    """Reference rank() semantics (IntDocVectorsForwardIndex.java:192-223),
    with float idf (the int-division quirk is tested separately)."""
    scores = {}
    for tid in query_tids:
        posts = postings_by_term.get(tid, [])
        dfv = len(posts)
        if dfv == 0:
            continue
        idf = np.log10(n_docs / dfv)
        for d, tf in posts:
            scores[d] = scores.get(d, 0.0) + (1 + np.log(tf)) * idf
    # engine semantics: zero-score docs (idf==0) are not returned
    ranked = sorted(
        ((d, s) for d, s in scores.items() if s > 0),
        key=lambda kv: (-kv[1], kv[0]))[:k]
    return ranked


def _small_index():
    rng = np.random.default_rng(1)
    n_tok, vocab, ndocs = 1500, 200, 17
    t = rng.integers(0, vocab, n_tok).astype(np.int32)
    d = rng.integers(1, ndocs + 1, n_tok).astype(np.int32)
    term_ids = np.full(4096, PAD_TERM, np.int32)
    doc_ids = np.zeros(4096, np.int32)
    term_ids[:n_tok] = t
    doc_ids[:n_tok] = d
    p = build_postings_jit(jnp.asarray(term_ids), jnp.asarray(doc_ids),
                           vocab_size=vocab, num_docs=ndocs)
    oracle = oracle_postings(t.tolist(), d.tolist())
    return p, oracle, vocab, ndocs


def test_tfidf_dense_matches_oracle():
    p, oracle, vocab, ndocs = _small_index()
    mat = dense_doc_matrix(p.pair_term, p.pair_doc, p.pair_tf,
                           vocab_size=vocab, num_docs=ndocs)
    queries = np.array([[0, 5], [3, -1], [28, 2], [7, 7]], np.int32)
    scores, docnos = tfidf_topk_dense(
        jnp.asarray(queries), mat, p.df, jnp.int32(ndocs), k=5)
    scores, docnos = np.asarray(scores), np.asarray(docnos)
    for qi, q in enumerate(queries):
        tids = [x for x in q.tolist() if x >= 0]
        want = oracle_tfidf(oracle, tids, ndocs, k=5)
        got = [(int(dn), float(s)) for s, dn in zip(scores[qi], docnos[qi]) if dn > 0]
        assert len(got) == len(want)
        for (gd, gs), (wd, ws) in zip(got, want):
            assert gs == pytest.approx(ws, rel=1e-4)
        # same doc set at equal scores (tie order may differ)
        assert {g[0] for g in got} == {w[0] for w in want}


def test_tfidf_sparse_matches_dense():
    p, oracle, vocab, ndocs = _small_index()
    mat = dense_doc_matrix(p.pair_term, p.pair_doc, p.pair_tf,
                           vocab_size=vocab, num_docs=ndocs)
    # build padded per-term postings from CSR
    indptr = np.asarray(p.indptr)
    pcap = int(np.max(np.diff(indptr)))
    post_docs = np.zeros((vocab, pcap), np.int32)
    post_tfs = np.zeros((vocab, pcap), np.int32)
    pd, pt = np.asarray(p.pair_doc), np.asarray(p.pair_tf)
    for tid in range(vocab):
        lo, hi = indptr[tid], indptr[tid + 1]
        post_docs[tid, : hi - lo] = pd[lo:hi]
        post_tfs[tid, : hi - lo] = pt[lo:hi]

    queries = np.array([[0, 5], [3, -1], [11, 2]], np.int32)
    s1, d1 = tfidf_topk_dense(jnp.asarray(queries), mat, p.df,
                              jnp.int32(ndocs), k=5)
    s2, d2 = tfidf_topk_sparse(jnp.asarray(queries), jnp.asarray(post_docs),
                               jnp.asarray(post_tfs), p.df, jnp.int32(ndocs),
                               num_docs=ndocs, k=5)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-4)
    # doc sets match per rank where scores are distinct
    assert (np.asarray(d1) == np.asarray(d2)).mean() > 0.9


def test_compat_int_idf():
    p, oracle, vocab, ndocs = _small_index()
    mat = dense_doc_matrix(p.pair_term, p.pair_doc, p.pair_tf,
                           vocab_size=vocab, num_docs=ndocs)
    q = np.array([[4, -1]], np.int32)
    s, dn = tfidf_topk_dense(jnp.asarray(q), mat, p.df, jnp.int32(ndocs),
                             k=3, compat_int_idf=True)
    dfv = int(np.asarray(p.df)[4])
    posts = oracle.get(4, [])
    want = sorted(
        ((1 + np.log(tf)) * np.log10(max(ndocs // dfv, 1e-30)), d)
        for d, tf in posts)[::-1][:3]
    got = [float(x) for x in np.asarray(s)[0] if x > 0]
    for g, (w, _) in zip(got, want):
        assert g == pytest.approx(w, rel=1e-4)


def test_tfidf_hybrid_matches_dense():
    """Hot/cold split layout must equal the dense path regardless of where
    the df threshold lands."""
    from tpu_ir.ops.scoring import tfidf_topk_hybrid

    p, oracle, vocab, ndocs = _small_index()
    mat = dense_doc_matrix(p.pair_term, p.pair_doc, p.pair_tf,
                           vocab_size=vocab, num_docs=ndocs)
    indptr = np.asarray(p.indptr)
    df = np.asarray(p.df)
    pd_, pt_ = np.asarray(p.pair_doc), np.asarray(p.pair_tf)

    for threshold in [0, 3, 10**9]:  # all-hot, mixed, all-cold
        hot_tids = np.nonzero(df > threshold)[0]
        hot_rank = np.full(vocab, -1, np.int32)
        hot_rank[hot_tids] = np.arange(len(hot_tids), dtype=np.int32)
        hot_rows = np.zeros((max(len(hot_tids), 1), ndocs + 1), np.float32)
        for r, tid in enumerate(hot_tids):
            lo, hi = indptr[tid], indptr[tid + 1]
            hot_rows[r, pd_[lo:hi]] = 1.0 + np.log(pt_[lo:hi])
        pcap = max(int(df[hot_rank < 0].max()) if (hot_rank < 0).any() else 1, 1)
        post_docs = np.zeros((vocab, pcap), np.int32)
        post_tfs = np.zeros((vocab, pcap), np.int32)
        for tid in range(vocab):
            if hot_rank[tid] >= 0:
                continue
            lo, hi = indptr[tid], indptr[tid + 1]
            post_docs[tid, : hi - lo] = pd_[lo:hi]
            post_tfs[tid, : hi - lo] = pt_[lo:hi]

        queries = np.array([[0, 5, 199], [3, -1, -1], [11, 2, 7]], np.int32)
        s1, d1 = tfidf_topk_dense(jnp.asarray(queries), mat, p.df,
                                  jnp.int32(ndocs), k=5)
        s2, d2 = tfidf_topk_hybrid(
            jnp.asarray(queries), jnp.asarray(hot_rank),
            jnp.asarray(hot_rows), jnp.asarray(post_docs),
            jnp.asarray(post_tfs), p.df, jnp.int32(ndocs),
            num_docs=ndocs, k=5)
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                                   rtol=1e-4, err_msg=str(threshold))
