"""Acceptance suite for the unified telemetry layer (ISSUE 3).

Pins the four contracts of tpu_ir.obs:

- histogram bucket math: boundary membership, percentile estimates
  within one bucket of exact, merge == histogram of concatenation;
- span trees: nesting, thread ids, cross-thread re-parenting through
  the deadline dispatcher, the bounded/sampled trace ring, and the
  TPU_IR_TRACE=0 near-no-op + the <=10% serving-overhead guard;
- coverage-by-construction: every fault-injection site found in the
  SOURCE has a declared fault.<site> counter, every service level the
  ladder can emit has a declared request.<level> histogram (no silently
  untelemetered failure path);
- the flight recorder: a forced soak invariant breach writes a JSONL
  artifact holding the offending request's full span tree plus a
  registry snapshot.
"""

import json
import math
import random
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import tpu_ir
import tpu_ir.faults as faults
from tpu_ir import obs
from tpu_ir.index.streaming import build_index_streaming
from tpu_ir.obs.histogram import (
    BOUNDS,
    NUM_BUCKETS,
    LatencyHistogram,
    bucket_index,
)
from tpu_ir.search import Scorer
from tpu_ir.serving import ServingConfig, ServingFrontend, run_soak
from tpu_ir.serving.soak import make_queries
from tpu_ir.utils.report import JobReport, recovery_counters

WORDS = ("salmon fishing river bears honey quick brown fox lazy dog "
         "market investor asset bond stock season rain forest".split())


@pytest.fixture(autouse=True)
def _restore_trace_config():
    """Tests below flip the runtime trace knobs; put the defaults back
    (the registry/ring themselves are reset by conftest's autouse
    telemetry fixture)."""
    yield
    obs.configure(enabled=True, sample=1, ring_capacity=64)
    faults.clear()


@pytest.fixture(scope="module")
def index_dir(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("obs")
    body = []
    for i in range(120):
        text = " ".join(WORDS[(i + j) % len(WORDS)]
                        for j in range(3 + (i % 7)))
        body.append(f"<DOC>\n<DOCNO> D-{i:04d} </DOCNO>\n<TEXT>\n"
                    f"{text}\n</TEXT>\n</DOC>\n")
    corpus = tmp / "corpus.trec"
    corpus.write_text("".join(body))
    out = str(tmp / "idx")
    build_index_streaming([str(corpus)], out, k=1, num_shards=3,
                          batch_docs=40, chargram_ks=[])
    return out


@pytest.fixture(scope="module")
def scorer(index_dir):
    s = Scorer.load(index_dir, layout="sparse")
    # warm every compile class the tests dispatch, so span timings and
    # the overhead guard measure serving, not XLA compilation
    s.search_batch(["salmon fishing"], k=5, scoring="bm25")
    s.search_batch(["salmon fishing"], k=5, scoring="tfidf")
    s.search_batch(["salmon fishing"], k=5, rerank=25)
    return s


# ---------------------------------------------------------------------------
# histogram bucket math
# ---------------------------------------------------------------------------


def test_bucket_boundaries_land_in_their_bucket():
    """Bucket i is (BOUNDS[i-1], BOUNDS[i]]: an exact boundary value
    belongs to the bucket it bounds, the next float up to the next."""
    assert bucket_index(0.0) == 0
    assert bucket_index(-1.0) == 0          # garbage clamps, never raises
    for i, b in enumerate(BOUNDS):
        assert bucket_index(b) == i
        assert bucket_index(math.nextafter(b, math.inf)) == \
            min(i + 1, NUM_BUCKETS - 1)
    assert bucket_index(1e9) == NUM_BUCKETS - 1   # overflow bucket


def test_percentiles_within_one_bucket_of_exact():
    rng = random.Random(42)
    h = LatencyHistogram()
    samples = [rng.lognormvariate(-7.0, 2.0) for _ in range(5000)]
    for s in samples:
        h.observe(s)
    for q in (50, 95, 99):
        est = h.percentile(q)
        exact = float(np.percentile(samples, q))
        assert abs(bucket_index(est) - bucket_index(exact)) <= 1, \
            f"p{q}: estimate {est} vs exact {exact}"


def test_merge_equals_histogram_of_concatenation():
    rng = random.Random(7)
    a = [rng.expovariate(100.0) for _ in range(800)]
    b = [rng.lognormvariate(-4.0, 1.5) for _ in range(1200)]
    ha, hb, hc = LatencyHistogram(), LatencyHistogram(), LatencyHistogram()
    for s in a:
        ha.observe(s)
    for s in b:
        hb.observe(s)
    for s in a + b:
        hc.observe(s)
    ha.merge(hb)
    counts_m, sum_m = ha.state()
    counts_c, sum_c = hc.state()
    assert counts_m == counts_c
    assert sum_m == pytest.approx(sum_c)
    assert ha.summary()["count"] == len(a) + len(b)


def test_empty_histogram_summary_is_well_formed():
    s = LatencyHistogram().summary()
    assert s["count"] == 0
    assert s["p50_ms"] is None and s["p99_ms"] is None


# ---------------------------------------------------------------------------
# spans + the trace ring
# ---------------------------------------------------------------------------


def test_span_tree_nesting_thread_ids_and_histograms():
    with obs.trace("outer", kind="test") as root:
        root.set("extra", 1)
        with obs.trace("mid"):
            with obs.trace("leaf"):
                pass
        with obs.trace("mid2"):
            pass
    traces = obs.recent_traces()
    assert len(traces) == 1
    t = traces[0]
    assert t.name == "outer" and t.attrs == {"kind": "test", "extra": 1}
    assert [c.name for c in t.children] == ["mid", "mid2"]
    assert t.children[0].children[0].name == "leaf"
    assert t.thread_id == threading.get_ident()
    assert t.dur_ns >= t.children[0].dur_ns >= 0
    d = t.to_dict()
    assert d["children"][0]["children"][0]["name"] == "leaf"
    assert "time" in d            # roots carry a wall-clock stamp
    # every span's duration also landed in the same-named histogram
    reg = obs.get_registry()
    for name in ("outer", "mid", "leaf", "mid2"):
        assert reg.histogram(name).count == 1


def test_span_records_escaping_exception():
    with pytest.raises(ValueError):
        with obs.trace("doomed"):
            raise ValueError("the reason")
    t = obs.recent_traces()[-1]
    assert t.name == "doomed" and "the reason" in t.error


def test_deadline_worker_spans_attach_to_caller_tree():
    """faults.run_with_deadline runs fn on a worker thread; its spans
    must re-parent onto the caller's request span, not surface as
    orphan roots."""
    def work():
        with obs.trace("inner"):
            time.sleep(0.005)
        return 42

    with obs.trace("req") as root:
        assert faults.run_with_deadline(work, deadline_s=5.0) == 42
    traces = obs.recent_traces()
    assert [t.name for t in traces] == ["req"]   # no orphan root
    inner = traces[0].children[0]
    assert inner.name == "inner"
    assert inner.thread_id != root.thread_id


def test_trace_ring_is_bounded_and_sampled():
    obs.configure(ring_capacity=8)
    for i in range(20):
        with obs.trace(f"r{i}"):
            pass
    names = [t.name for t in obs.recent_traces()]
    assert names == [f"r{i}" for i in range(12, 20)]
    obs.clear_traces()
    obs.configure(sample=3, ring_capacity=64)
    for i in range(9):
        with obs.trace(f"s{i}"):
            pass
    assert len(obs.recent_traces()) == 3        # every 3rd root kept
    # histograms record regardless of ring sampling
    assert obs.get_registry().histogram("s1").count == 1


def test_disabled_tracing_is_near_noop():
    """TPU_IR_TRACE=0: trace() is one flag test returning a shared
    no-op — a tight loop must be effectively free (generous bound) and
    leave no state anywhere."""
    obs.configure(enabled=False)
    with obs.trace("off") as sp:     # the null span still quacks
        sp.set("k", "v")
    assert obs.recent_traces() == []
    assert obs.get_registry().histogram("off").count == 0
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        with obs.trace("off"):
            pass
    dt = time.perf_counter() - t0
    assert dt < 2.0, f"{n} disabled spans took {dt:.3f}s"


def test_disabled_tracing_silences_request_histograms(scorer):
    """TPU_IR_TRACE=0 turns off ALL latency histograms — the span-fed
    stage ones AND the frontend's direct request.<level> observes —
    while the serving counters keep counting (the documented split)."""
    obs.configure(enabled=False)
    frontend = ServingFrontend(scorer)
    res = frontend.search("salmon fishing", k=5)
    assert res.level == "full"
    reg = obs.get_registry()
    assert reg.histogram("request.full").count == 0
    assert reg.histogram("dispatch").count == 0
    assert reg.get("serving.submitted") == 1     # counters stay live


def test_tracing_overhead_within_ten_percent_of_disabled(scorer):
    """The overhead guard: a 200-query CPU serving soak with tracing
    enabled (default sampling) stays within 10% of tracing-disabled
    (plus a small absolute slack so scheduler noise on a loaded CI box
    cannot flake a sub-second measurement)."""
    reqs = make_queries(scorer, 200, seed=7)
    frontend = ServingFrontend(scorer, ServingConfig(
        max_concurrency=4, max_queue=16))

    def soak_once() -> float:
        t0 = time.perf_counter()
        for r in reqs:
            frontend.search(r["text"], k=r["k"], scoring=r["scoring"],
                            rerank=r["rerank"])
        return time.perf_counter() - t0

    soak_once()                      # warm every query shape
    timings = {}
    for enabled in (True, False):
        obs.configure(enabled=enabled)
        timings[enabled] = min(soak_once() for _ in range(2))
    obs.configure(enabled=True)
    assert timings[True] <= timings[False] * 1.10 + 0.15, (
        f"tracing overhead too high: traced {timings[True]:.3f}s vs "
        f"untraced {timings[False]:.3f}s")


# ---------------------------------------------------------------------------
# registry: unification, reset, exports
# ---------------------------------------------------------------------------


def test_counter_aliases_are_registry_views():
    reg = obs.get_registry()
    recovery_counters().incr("retries", 3)
    assert reg.get("recovery.retries") == 3
    assert recovery_counters().snapshot()["retries"] == 3
    reg.incr("recovery.quarantined")
    assert recovery_counters().get("quarantined") == 1
    # the alias reset clears ONLY its namespace
    reg.incr("serving.submitted", 5)
    recovery_counters().reset()
    assert recovery_counters().snapshot() == {}
    assert reg.get("serving.submitted") == 5


def test_snapshot_reset_stops_bleed_through():
    reg = obs.get_registry()
    reg.incr("serving.submitted", 4)
    reg.observe("dispatch", 0.01)
    first = reg.snapshot(reset=True)
    assert first["counters"]["serving.submitted"] == 4
    assert first["histograms"]["dispatch"]["count"] == 1
    second = reg.snapshot()
    assert "serving.submitted" not in second["counters"]
    assert second["histograms"]["dispatch"]["count"] == 0
    # declared names survive a reset at zero (presence is the contract)
    assert "fault.score.hang" in second["counters"]


def test_fault_fires_land_in_registry():
    faults.install(faults.parse_plan("score.device_loss:first@2"))
    faults.should_fire("score.device_loss")
    faults.should_fire("score.device_loss")
    faults.should_fire("score.device_loss")   # spec exhausted: no fire
    assert obs.get_registry().get("fault.score.device_loss") == 2


def test_jobreport_phases_feed_build_histograms():
    rep = JobReport("UnitTestJob")
    with rep.phase("tokenize"):
        time.sleep(0.001)
    with rep.phase("tokenize"):
        pass
    assert obs.get_registry().histogram("build.tokenize").count == 2
    assert rep.timings_s["tokenize"] > 0
    roots = [t.name for t in obs.recent_traces()]
    assert roots.count("build.tokenize") == 2


def test_prometheus_exposition_shape():
    reg = obs.get_registry()
    reg.incr("serving.submitted", 2)
    reg.observe("dispatch", 0.003)
    text = reg.prometheus_text()
    assert '# TYPE tpu_ir_events_total counter' in text
    assert 'tpu_ir_events_total{name="serving.submitted"} 2' in text
    assert '# TYPE tpu_ir_stage_latency_seconds histogram' in text
    # every family carries a # HELP line immediately before its # TYPE
    lines = text.splitlines()
    for family in ("tpu_ir_events_total", "tpu_ir_gauge",
                   "tpu_ir_stage_latency_seconds"):
        help_ln = [i for i, ln in enumerate(lines)
                   if ln.startswith(f"# HELP {family} ")]
        assert len(help_ln) == 1, f"missing # HELP for {family}"
        assert lines[help_ln[0] + 1].startswith(f"# TYPE {family} ")
    assert 'le="+Inf"}' in text
    assert 'tpu_ir_stage_latency_seconds_count{stage="dispatch"} 1' in text
    # buckets are cumulative: +Inf count equals the _count line
    disp = [ln for ln in text.splitlines() if 'stage="dispatch"' in ln]
    inf = [ln for ln in disp if 'le="+Inf"' in ln][0]
    assert inf.rsplit(" ", 1)[1] == "1"


# ---------------------------------------------------------------------------
# coverage by construction (the static-analysis-style tests)
# ---------------------------------------------------------------------------

# PR 3's regex-based source scans for fault-site and service-level
# coverage now live in tpu_ir/lint/contracts.py (ISSUE 6) as AST-precise
# contract passes shared with `tpu-ir lint`; these tests are thin
# wrappers pinning (a) the passes still SEE the package (a rotted scan
# reports nothing, which must fail here, not pass silently) and (b) the
# runtime registry honors what the passes verified statically.


@pytest.fixture(scope="module")
def _lint_index():
    from tpu_ir.lint import PackageIndex

    pkg = Path(tpu_ir.__file__).parent
    return PackageIndex(str(pkg), rel_root=str(pkg.parent))


def test_every_injection_site_in_source_is_declared_and_registered(
        _lint_index):
    """Every fault-injection call site found in the source must be in
    obs.FAULT_SITES AND have a pre-registered fault.<site> counter — a
    failure path cannot exist untelemetered. (Logic: lint TPU304.)"""
    from tpu_ir.lint import contracts

    found = contracts.collect_fault_sites(_lint_index)
    assert found, "no injection sites found — the lint scan rotted"
    violations = [f for f in contracts.check(_lint_index)
                  if f.rule == "TPU304"]
    assert not violations, violations
    names = set(obs.get_registry().counter_names())
    for site in obs.FAULT_SITES:
        assert f"fault.{site}" in names


def test_every_service_level_has_a_request_histogram(_lint_index):
    """Every LEVEL_* the frontend's ladder can emit must appear in the
    declared histogram label set (request.<level>) and be registered.
    (Logic: lint TPU305's service-level drift check.)"""
    from tpu_ir.lint import contracts

    levels = contracts.collect_service_levels(_lint_index)
    assert levels == set(obs.SERVICE_LEVELS)
    violations = [f for f in contracts.check(_lint_index)
                  if f.rule == "TPU305"]
    assert not violations, violations
    registered = set(obs.get_registry().histogram_names())
    for lv in levels:
        assert f"request.{lv}" in obs.DECLARED_HISTOGRAMS
        assert f"request.{lv}" in registered


def test_request_stage_histograms_are_declared():
    registered = set(obs.get_registry().histogram_names())
    for stage in ("admission_wait", "ladder", "breaker", "dispatch",
                  "kernel", "fallback"):
        assert stage in obs.REQUEST_STAGES
        assert stage in registered


# ---------------------------------------------------------------------------
# the serving span tree + latency breakdown
# ---------------------------------------------------------------------------


def test_request_span_tree_and_level_histogram(scorer):
    frontend = ServingFrontend(scorer)
    res = frontend.search("salmon fishing", k=5)
    assert res.level == "full"
    req = [t for t in obs.recent_traces() if t.name == "request"][-1]
    child_names = [c.name for c in req.children]
    assert child_names[:3] == ["ladder", "admission_wait", "breaker"]
    assert "dispatch" in child_names
    disp = req.children[child_names.index("dispatch")]
    assert any(c.name == "kernel" for c in disp.children)
    assert req.attrs["level"] == "full"
    reg = obs.get_registry()
    assert reg.histogram("request.full").count == 1
    assert reg.histogram("admission_wait").count == 1


def test_soak_reports_stage_latency_breakdown(scorer):
    report = run_soak(
        scorer, threads=4, queries=40, seed=3, fault_spec=None,
        config=ServingConfig(max_concurrency=4, max_queue=16,
                             deadline_s=5.0),
        timeout_s=60.0)
    lat = report["latency"]
    # the acceptance stages are always present, observed or not
    for stage in ("admission_wait", "dispatch", "kernel", "fallback"):
        assert stage in lat
        for key in ("count", "p50_ms", "p95_ms", "p99_ms"):
            assert key in lat[stage]
    assert lat["dispatch"]["count"] == 40
    assert lat["dispatch"]["p50_ms"] > 0
    assert lat["fallback"]["count"] == 0        # healthy run
    assert lat["request.full"]["count"] == 40
    assert "flight_record" not in report        # no breach, no dump


def test_soak_breach_writes_flight_record_with_span_tree(
        scorer, tmp_path):
    """The acceptance criterion: a forced soak invariant breach produces
    a flight-recorder JSONL containing the offending request's full span
    tree (plus header + telemetry snapshot)."""
    orig = scorer.search_batch
    calls = {"n": 0}

    def flaky(texts, **kw):
        # only frontend-originated calls carry force_host; the soak's
        # serial reference phase must stay clean
        if "force_host" in kw:
            calls["n"] += 1
            if calls["n"] % 5 == 0:
                raise RuntimeError("injected unstructured boom")
        return orig(texts, **kw)

    scorer.search_batch = flaky
    try:
        report = run_soak(
            scorer, threads=4, queries=30, seed=1, fault_spec=None,
            config=ServingConfig(max_concurrency=4, max_queue=16,
                                 deadline_s=5.0),
            timeout_s=60.0, flight_dir=str(tmp_path))
    finally:
        scorer.search_batch = orig
    assert report["errors"] > 0
    path = report["flight_record"]
    assert path and Path(path).exists()
    recs = [json.loads(line) for line in open(path)]
    assert recs[0]["record"] == "header"
    assert recs[0]["reason"] == "soak_invariant_breach"
    assert recs[0]["extra"]["errors"] == report["errors"]
    assert recs[-1]["record"] == "telemetry"
    assert "counters" in recs[-1]["telemetry"]
    offenders = [r["trace"] for r in recs if r["record"] == "trace"
                 and "boom" in r["trace"].get("error", "")]
    assert offenders, "the offending request's trace is not in the dump"
    names = {c["name"] for c in offenders[0]["children"]}
    assert {"ladder", "admission_wait", "breaker"} <= names


def test_breaker_open_triggers_rate_limited_dump(scorer, tmp_path,
                                                 monkeypatch):
    monkeypatch.setenv("TPU_IR_FLIGHT_DIR", str(tmp_path))
    frontend = ServingFrontend(scorer, ServingConfig(
        breaker_threshold=2, deadline_s=5.0))
    faults.install(faults.parse_plan("score.device_loss:first@8"))
    for _ in range(3):
        res = frontend.search("salmon fishing", k=5)
        assert res.degraded
    faults.clear()
    dumps = list(tmp_path.glob("flight-*breaker_open.jsonl"))
    assert len(dumps) == 1      # opened once -> one dump, rate-limited


# ---------------------------------------------------------------------------
# CLI surfaces
# ---------------------------------------------------------------------------


def test_metrics_cli_json_and_reset(capsys):
    from tpu_ir.cli import main

    reg = obs.get_registry()
    reg.incr("serving.submitted", 7)
    reg.observe("dispatch", 0.002)
    assert main(["metrics"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["counters"]["serving.submitted"] == 7
    assert out["histograms"]["dispatch"]["count"] == 1
    assert main(["metrics", "--reset"]) == 0
    capsys.readouterr()
    assert reg.get("serving.submitted") == 0


def test_metrics_cli_prometheus(capsys):
    from tpu_ir.cli import main

    obs.get_registry().incr("serving.submitted", 3)
    assert main(["metrics", "--prom"]) == 0
    text = capsys.readouterr().out
    assert 'tpu_ir_events_total{name="serving.submitted"} 3' in text
    assert "# TYPE tpu_ir_stage_latency_seconds histogram" in text


def test_trace_dump_cli(tmp_path, capsys):
    from tpu_ir.cli import main

    with obs.trace("cli-root"):
        with obs.trace("cli-child"):
            pass
    out_file = tmp_path / "dump.jsonl"
    assert main(["trace-dump", "--out", str(out_file)]) == 0
    meta = json.loads(capsys.readouterr().out)
    assert meta["traces"] == 1
    recs = [json.loads(line) for line in out_file.open()]
    # same artifact shape as a breach dump: header first, traces, snapshot
    assert recs[0]["record"] == "header"
    assert recs[0]["reason"] == "manual_trace_dump"
    assert recs[1]["record"] == "trace"
    assert recs[1]["trace"]["name"] == "cli-root"
    assert recs[1]["trace"]["children"][0]["name"] == "cli-child"
    assert recs[-1]["record"] == "telemetry"
    # stdout form: one JSON object per line
    assert main(["trace-dump"]) == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert all(json.loads(ln) for ln in lines)


def test_stats_cli_reset_flag(capsys):
    from tpu_ir.cli import main

    recovery_counters().incr("retries", 2)
    assert main(["stats", "--reset"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["recovery"]["retries"] == 2
    assert main(["stats"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["recovery"] == {}
