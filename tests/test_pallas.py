"""Pallas fused-scoring kernel vs the XLA dense path (interpret mode on the
CPU suite; compiled on real TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_ir.ops import PAD_TERM, build_postings_jit, dense_doc_matrix, tfidf_topk_dense

# This container's CPU-only jaxlib may lack the TPU MLIR platform that the
# pallas import path registers lowerings for; skip cleanly in that case.
try:
    from tpu_ir.ops.pallas_scoring import pallas_tfidf_topk
except Exception as e:  # NotImplementedError from mlir platform registry
    pytest.skip(f"pallas unavailable on this jaxlib build: {e}",
                allow_module_level=True)

INTERPRET = jax.devices()[0].platform != "tpu"


@pytest.fixture(scope="module")
def index_data():
    rng = np.random.default_rng(5)
    n_tok, vocab, ndocs = 3000, 128, 127  # D+1 = 128-aligned
    t = rng.integers(0, vocab, n_tok).astype(np.int32)
    d = rng.integers(1, ndocs + 1, n_tok).astype(np.int32)
    term_ids = np.full(4096, PAD_TERM, np.int32)
    doc_ids = np.zeros(4096, np.int32)
    term_ids[:n_tok] = t
    doc_ids[:n_tok] = d
    p = build_postings_jit(jnp.asarray(term_ids), jnp.asarray(doc_ids),
                           vocab_size=vocab, num_docs=ndocs)
    mat = dense_doc_matrix(p.pair_term, p.pair_doc, p.pair_tf,
                           vocab_size=vocab, num_docs=ndocs)
    return mat, p.df, ndocs


def test_pallas_matches_xla(index_data):
    mat, df, ndocs = index_data
    rng = np.random.default_rng(6)
    q = rng.integers(0, 128, (16, 3)).astype(np.int32)
    q[3, 1] = -1  # padding
    q[7, :] = -1  # empty query
    s1, d1 = tfidf_topk_dense(jnp.asarray(q), mat, df, jnp.int32(ndocs), k=10)
    s2, d2 = pallas_tfidf_topk(jnp.asarray(q), mat, df, jnp.int32(ndocs),
                               k=10, interpret=INTERPRET)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-5)
    # identical scores imply same doc sets; tie order may differ
    for qi in range(q.shape[0]):
        assert set(np.asarray(d1)[qi].tolist()) == \
            set(np.asarray(d2)[qi].tolist()), qi


def test_pallas_duplicate_terms(index_data):
    mat, df, ndocs = index_data
    q = np.array([[4, 4, 4]], np.int32)  # repeated term accumulates 3x
    s1, d1 = tfidf_topk_dense(jnp.asarray(q), mat, df, jnp.int32(ndocs), k=5)
    s2, d2 = pallas_tfidf_topk(jnp.asarray(q), mat, df, jnp.int32(ndocs),
                               k=5, interpret=INTERPRET)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-5)


def test_scorer_pallas_layout_matches_dense(tmp_path):
    """layout='pallas' on the Scorer (interpret off-TPU) must rank exactly
    like layout='dense'; bm25 on the pallas layout falls back to XLA."""
    from tpu_ir.index import build_index
    from tpu_ir.search import Scorer

    rng = np.random.default_rng(11)
    words = ["w%03d" % i for i in range(60)]
    corpus = tmp_path / "c.trec"
    with open(corpus, "w") as f:
        for i in range(40):
            body = " ".join(rng.choice(words, 30))
            f.write(f"<DOC>\n<DOCNO> D-{i:03d} </DOCNO>\n<TEXT>\n{body}\n"
                    f"</TEXT>\n</DOC>\n")
    idx = str(tmp_path / "idx")
    build_index([str(corpus)], idx, k=1, chargram_ks=[],
                compute_chargrams=False)

    dense = Scorer.load(idx, layout="dense")
    pall = Scorer.load(idx, layout="pallas")
    assert pall.layout == "pallas"
    queries = ["w001 w005", "w010", "w020 w030 w040"]
    for scoring in ("tfidf", "bm25"):
        r1 = dense.search_batch(queries, k=5, scoring=scoring)
        r2 = pall.search_batch(queries, k=5, scoring=scoring)
        # like the kernel tests above: docno sets + approx scores (ties may
        # reorder under 1-ulp accumulation differences kernel vs einsum)
        for q1, q2 in zip(r1, r2):
            assert {d for d, _ in q1} == {d for d, _ in q2}, scoring
            np.testing.assert_allclose(
                sorted(s for _, s in q1), sorted(s for _, s in q2),
                rtol=1e-5, err_msg=scoring)
