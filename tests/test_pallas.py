"""Pallas fused-scoring kernel vs the XLA dense path (interpret mode on the
CPU suite; compiled on real TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_ir.ops import PAD_TERM, build_postings_jit, dense_doc_matrix, tfidf_topk_dense

# This container's CPU-only jaxlib may lack the TPU MLIR platform that the
# pallas import path registers lowerings for; skip cleanly in that case.
try:
    from tpu_ir.ops.pallas_scoring import pallas_tfidf_topk
except Exception as e:  # NotImplementedError from mlir platform registry
    pytest.skip(f"pallas unavailable on this jaxlib build: {e}",
                allow_module_level=True)

INTERPRET = jax.devices()[0].platform != "tpu"


@pytest.fixture(scope="module")
def index_data():
    rng = np.random.default_rng(5)
    n_tok, vocab, ndocs = 3000, 128, 127  # D+1 = 128-aligned
    t = rng.integers(0, vocab, n_tok).astype(np.int32)
    d = rng.integers(1, ndocs + 1, n_tok).astype(np.int32)
    term_ids = np.full(4096, PAD_TERM, np.int32)
    doc_ids = np.zeros(4096, np.int32)
    term_ids[:n_tok] = t
    doc_ids[:n_tok] = d
    p = build_postings_jit(jnp.asarray(term_ids), jnp.asarray(doc_ids),
                           vocab_size=vocab, num_docs=ndocs)
    mat = dense_doc_matrix(p.pair_term, p.pair_doc, p.pair_tf,
                           vocab_size=vocab, num_docs=ndocs)
    return mat, p.df, ndocs


def test_pallas_matches_xla(index_data):
    mat, df, ndocs = index_data
    rng = np.random.default_rng(6)
    q = rng.integers(0, 128, (16, 3)).astype(np.int32)
    q[3, 1] = -1  # padding
    q[7, :] = -1  # empty query
    s1, d1 = tfidf_topk_dense(jnp.asarray(q), mat, df, jnp.int32(ndocs), k=10)
    s2, d2 = pallas_tfidf_topk(jnp.asarray(q), mat, df, jnp.int32(ndocs),
                               k=10, interpret=INTERPRET)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-5)
    # identical scores imply same doc sets; tie order may differ
    for qi in range(q.shape[0]):
        assert set(np.asarray(d1)[qi].tolist()) == \
            set(np.asarray(d2)[qi].tolist()), qi


def test_pallas_duplicate_terms(index_data):
    mat, df, ndocs = index_data
    q = np.array([[4, 4, 4]], np.int32)  # repeated term accumulates 3x
    s1, d1 = tfidf_topk_dense(jnp.asarray(q), mat, df, jnp.int32(ndocs), k=5)
    s2, d2 = pallas_tfidf_topk(jnp.asarray(q), mat, df, jnp.int32(ndocs),
                               k=5, interpret=INTERPRET)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-5)


# NOTE: the Scorer's `--layout pallas` serving option was retired in round 2
# after hardware measurement (NOTES.md "Pallas verdict"): the kernel is 2x
# slower than XLA's einsum at ref scale and the cold-tier scatter it might
# have fused runs at memory bandwidth under XLA already (0.06 ms per
# 64-query block at 1M docs). The kernel itself stays, exercised by the
# parity tests above — the scalar-prefetch row-DMA pattern is the reusable
# piece, not the layout flag.
