"""Real 2-process jax.distributed test on CPU: file slicing, string
allgather, and a cross-process psum — the host-level half of multi-host
support. Spawned as subprocesses so each gets its own JAX runtime."""

import os
import socket
import subprocess
import sys

import pytest

WORKER = r"""
import os, sys, json
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
import jax._src.xla_bridge as xb
for n in list(xb._backend_factories):
    if n != "cpu":
        xb._backend_factories.pop(n, None)

coordinator, pid = sys.argv[1], int(sys.argv[2])
from tpu_ir.parallel.multihost import (
    init_distributed, process_file_slice, allgather_strings)

pi, pc = init_distributed(coordinator, num_processes=2, process_id=pid)
assert (pi, pc) == (pid, 2), (pi, pc)

files = [f"f{i}" for i in range(5)]
mine = process_file_slice(files, pi, pc)

terms = ["apple", "zebra"] if pid == 0 else ["mango", "apple"]
union = allgather_strings(terms)

# chunked rounds across real processes: asymmetric set sizes, tiny chunks
# (forces many rounds + mid-line chunk splits), exact union required
many0 = [f"shared-term-{i:04d}" for i in range(200)]
many1 = many0[::2] + [f"only-p1-{i:04d}" for i in range(75)]
u2 = allgather_strings(many0 if pid == 0 else many1, chunk_bytes=64)
chunked_ok = u2 == sorted(set(many0) | set(many1))

import jax.numpy as jnp
total = int(jax.experimental.multihost_utils.process_allgather(
    jnp.int32(pid + 1)).sum())

# --- global 4-device mesh (2 hosts x 2 devices) SPMD index build ---
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from tpu_ir.parallel import make_mesh, sharded_build_postings
from tpu_ir.ops.postings import PAD_TERM

S, C, V, NDOCS = 4, 512, 50, 32
rng = np.random.default_rng(0)  # same data generated on both processes
t_all = rng.integers(0, V, (S, C // 2)).astype(np.int32)
d_all = rng.integers(1, NDOCS + 1, (S, C // 2)).astype(np.int32)
term_ids = np.full((S, C), PAD_TERM, np.int32); term_ids[:, :C // 2] = t_all
doc_ids = np.zeros((S, C), np.int32); doc_ids[:, :C // 2] = d_all
docs_per_shard = np.full(S, NDOCS // S, np.int32)

mesh = make_mesh(S)
sh2 = NamedSharding(mesh, P("shards", None))
sh1 = NamedSharding(mesh, P("shards"))
lo, hi = pid * 2, pid * 2 + 2
g_t = jax.make_array_from_process_local_data(sh2, term_ids[lo:hi], (S, C))
g_d = jax.make_array_from_process_local_data(sh2, doc_ids[lo:hi], (S, C))
g_n = jax.make_array_from_process_local_data(sh1, docs_per_shard[lo:hi], (S,))
out = sharded_build_postings(g_t, g_d, g_n, vocab_size=V, total_docs=NDOCS,
                             mesh=mesh)

# oracle over the full data, checked against this process's term shards
from collections import Counter
counts = Counter(zip(t_all.ravel().tolist(), d_all.ravel().tolist()))
mesh_ok = True
for shard_data in out.pair_term.addressable_shards:
    s_idx = shard_data.index[0].start
    pt = np.asarray(shard_data.data).ravel()
    npairs_local = int(np.asarray(
        out.num_pairs.addressable_shards[
            [sd.index[0].start for sd in
             out.num_pairs.addressable_shards].index(s_idx)].data).ravel()[0])
    pt = pt[:npairs_local]
    want_pairs = sum(1 for (tt, dd) in counts if tt % S == s_idx)
    if npairs_local != want_pairs or not ((pt % S) == s_idx).all():
        mesh_ok = False
n_docs_out = int(np.asarray(out.num_docs.addressable_shards[0].data).ravel()[0])
mesh_ok = mesh_ok and n_docs_out == NDOCS

print(json.dumps({"pid": pid, "mine": mine, "union": union, "total": total,
                  "mesh_ok": mesh_ok, "chunked_ok": chunked_ok}))
"""


@pytest.mark.skipif(os.environ.get("TPU_IR_SKIP_MULTIHOST") == "1",
                    reason="multihost test disabled")
def test_two_process_distributed(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(WORKER)

    env = {**os.environ, "PYTHONPATH": os.getcwd()}
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coordinator, str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            cwd=os.getcwd(), text=True)
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=180)
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(out)

    import json
    results = {r["pid"]: r for r in
               (json.loads(o.strip().splitlines()[-1]) for o in outs)}
    # round-robin file split covers all files disjointly
    assert results[0]["mine"] == ["f0", "f2", "f4"]
    assert results[1]["mine"] == ["f1", "f3"]
    # string union identical on both processes
    assert results[0]["union"] == results[1]["union"] == \
        ["apple", "mango", "zebra"]
    # cross-process collective worked
    assert results[0]["total"] == results[1]["total"] == 3
    # the SPMD index build ran over the global 2-host mesh correctly
    assert results[0]["mesh_ok"] and results[1]["mesh_ok"]
    # chunked string exchange (64-byte rounds) reassembled exactly
    assert results[0]["chunked_ok"] and results[1]["chunked_ok"]


def test_allgather_strings_bounded_exchange(monkeypatch):
    """Simulated 8-process collective over a large vocab: the stub stands
    in for multihost_utils.process_allgather (replaying what every process
    would contribute at each lockstep round, since the call sequence is
    deterministic) and RECORDS each round's exchange size. The union must
    be exact and no single round may materialize more than P * chunk_bytes
    — the padded-matrix implementation this replaces allocated
    P * rows * max_width up front (multiple GB at 1M terms)."""
    import numpy as np

    import tpu_ir.parallel.multihost as mh

    P_ = 8
    chunk = 1 << 16
    vocabs = [[f"term-{(i * 7 + p) % 200_000:06d}-suffix"
               for i in range(120_000)] for p in range(P_)]
    blobs = [b"\n".join(s.encode() for s in sorted(set(v))) for v in vocabs]
    sizes = np.array([len(b) for b in blobs], np.int64)
    state = {"round": 0, "max_gathered": 0}

    def fake_allgather(x):
        x = np.asarray(x)
        if x.ndim == 0:                       # the size negotiation
            return sizes.copy()
        ofs = state["round"] * chunk
        state["round"] += 1
        width = x.shape[0]
        out = np.zeros((P_, width), np.uint8)
        for p in range(P_):
            piece = blobs[p][ofs : ofs + width]
            out[p, : len(piece)] = np.frombuffer(piece, np.uint8)
        # caller's process-0 chunk must equal what the stub replays
        np.testing.assert_array_equal(x, out[0])
        state["max_gathered"] = max(state["max_gathered"], out.nbytes)
        return out

    monkeypatch.setattr(mh.jax, "process_count", lambda: P_)
    monkeypatch.setattr("jax.experimental.multihost_utils.process_allgather",
                        fake_allgather)
    got = mh.allgather_strings(vocabs[0], chunk_bytes=chunk)

    want = sorted(set().union(*vocabs))
    assert got == want and len(got) == 200_000
    assert state["round"] == -(-int(sizes.max()) // chunk)  # lockstep rounds
    assert state["max_gathered"] <= P_ * chunk  # bounded exchange memory
