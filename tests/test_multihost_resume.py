"""Multi-host build crash resume (VERDICT r3 item 1): the streaming
build's pass-DAG resume generalized across processes. A 2-process build
killed mid-pass-2 must restart WITHOUT re-tokenizing, skip the completed
lockstep batches on every process together, and produce artifacts
byte-identical to the single-process streaming build. A process that
LOST its local spills must force everyone's pass-2 state to be discarded
(the allgather'd agreement) while the surviving process still resumes
its own pass-1 spills."""

import filecmp
import json
import os
import shutil
import socket
import subprocess
import sys
import time

import numpy as np

# 6 files, round-robin to 2 processes -> 3 files each; the chunked
# tokenizer yields one delta per (small) file and batch_docs=2 flushes
# each delta as one spill batch, so every process runs 3 lockstep steps
DOCS = {
    "A-1": "alpha bravo charlie alpha", "A-2": "delta echo foxtrot bravo",
    "B-1": "charlie juliet kilo lima", "B-2": "echo mike november oscar",
    "C-1": "sierra tango uniform bravo", "C-2": "victor whiskey xray charlie",
    "D-1": "bravo charlie delta echo", "D-2": "foxtrot golf alpha india",
    "E-1": "golf hotel india alpha", "E-2": "papa quebec romeo alpha",
    "F-1": "yankee zulu alpha delta", "F-2": "hotel kilo mike zulu",
}
FILES = ["A", "B", "C", "D", "E", "F"]

# worker: 2 CPU devices per process; crash / forbid-tokenize injection via
# env so the SAME script runs the crashing pass and the resuming pass
WORKER = r"""
import os, sys, json
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
import jax._src.xla_bridge as xb
for n in list(xb._backend_factories):
    if n != "cpu":
        xb._backend_factories.pop(n, None)

coordinator, pid, corpus_dir, index_dir = (
    sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4])
crash_step = int(os.environ.get("TEST_CRASH_STEP", "0"))
crash_pid = int(os.environ.get("TEST_CRASH_PID", "-1"))
kill_step = int(os.environ.get("TEST_SIGKILL_STEP", "0"))
kill_pid = int(os.environ.get("TEST_SIGKILL_PID", "-1"))
forbid_tok = os.environ.get("TEST_FORBID_TOKENIZE", "").split(",")

import tpu_ir.parallel.sharded_build as sb
import tpu_ir.analysis.native as native

real_build = sb.sharded_build_postings
steps = {"n": 0}

def counting(*a, **kw):
    steps["n"] += 1
    if pid == crash_pid and crash_step and steps["n"] == crash_step:
        raise RuntimeError("injected pass-2 crash")
    if pid == kill_pid and kill_step and steps["n"] == kill_step:
        # a REAL kill: no unwinding, no atexit, no finally blocks — the
        # closest in-process stand-in for a preempted/OOM-killed host
        import signal
        os.kill(os.getpid(), signal.SIGKILL)
    return real_build(*a, **kw)

sb.sharded_build_postings = counting
if str(pid) in forbid_tok:
    def boom(*a, **kw):
        raise AssertionError("resume must not re-tokenize")
    native.make_chunked_tokenizer = boom
if int(os.environ.get("TEST_CRASH_PASS3_PID", "-1")) == pid:
    import tpu_ir.index.streaming as streaming
    def boom3(*a, **kw):
        raise RuntimeError("injected pass-3 crash")
    streaming.reduce_shard_spills = boom3

from tpu_ir.parallel.multihost import init_distributed, build_index_multihost

init_distributed(coordinator, num_processes=2, process_id=pid)
try:
    meta = build_index_multihost([corpus_dir], index_dir, k=1,
                                 compute_chargrams=False, batch_docs=2,
                                 positions=True)
except Exception as e:
    # hard exit: a crashed worker must DIE like a killed process, not
    # hang in jax.distributed's atexit barrier (which also swallows
    # SIGTERM via the preemption notifier)
    print("CRASHED: %s" % e, file=sys.stderr)
    sys.stderr.flush()
    os._exit(17)
print(json.dumps({"pid": pid, "steps": steps["n"],
                  "num_docs": meta.num_docs}))
"""


def write_corpus(tmp_path):
    corpus_dir = tmp_path / "corpus"
    corpus_dir.mkdir()
    for name in FILES:
        (corpus_dir / f"{name}.trec").write_text("".join(
            f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
            for d, t in DOCS.items() if d.startswith(name)))
    return corpus_dir


def spill_batches(index_dir, pid):
    """(n_batches from the pass-1 manifest, list of complete pair-spill
    batches) for one process's local spill dir."""
    spill = os.path.join(index_dir, f"_spill-p{pid:03d}")
    with np.load(os.path.join(spill, "pass1.npz"), allow_pickle=False) as z:
        n_batches = int(z["n_batches"])
    rows = [pid * 2, pid * 2 + 1]
    done = [b for b in range(n_batches)
            if all(os.path.exists(os.path.join(
                spill, f"pairs-{r:03d}-{b:05d}.npz")) for r in rows)]
    return n_batches, done


def run_workers(tmp_path, corpus_dir, index_dir, *, env_extra,
                expect_fail_pid=None, expect_signal=None, timeout=240):
    """Launch 2 worker processes; returns {pid: parsed stdout JSON} for
    the ones expected to succeed. When `expect_fail_pid` is set, that
    worker must exit nonzero and its partner (blocked in the next
    collective) is killed."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = {**os.environ, "PYTHONPATH": os.getcwd(), **env_extra}
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), f"127.0.0.1:{port}", str(pid),
             str(corpus_dir), index_dir],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            cwd=os.getcwd(), text=True)
        for pid in range(2)
    ]
    out = {}
    if expect_fail_pid is not None:
        crashed = procs[expect_fail_pid]
        _, err = crashed.communicate(timeout=timeout)
        if expect_signal is not None:
            assert crashed.returncode == -expect_signal, \
                (crashed.returncode, err[-2000:])
        else:
            assert crashed.returncode == 17, err[-2000:]
            assert "injected pass-" in err
        other = procs[1 - expect_fail_pid]
        # grace period before killing the partner: it may still be
        # draining its current batch's spill writes before it blocks in
        # the next collective — killing it mid-write would race the
        # "batch 0 complete on both processes" fixture state the resume
        # assertions depend on
        time.sleep(3)
        other.kill()  # partner is lockstep-blocked in a collective
        other.communicate(timeout=timeout)
        return out
    for pid, p in enumerate(procs):
        stdout, err = p.communicate(timeout=timeout)
        assert p.returncode == 0, f"worker {pid} failed:\n{err[-4000:]}"
        out[pid] = json.loads(stdout.strip().splitlines()[-1])
    return out


def build_reference(tmp_path, corpus_dir):
    from tpu_ir.index.streaming import build_index_streaming

    ref_dir = str(tmp_path / "ref_index")
    build_index_streaming([str(corpus_dir)], ref_dir, k=1, num_shards=4,
                          batch_docs=2, compute_chargrams=False,
                          positions=True)
    return ref_dir


def assert_identical_to_reference(index_dir, ref_dir):
    from tpu_ir.index import format as fmt
    from tpu_ir.index.positions import positions_name
    from tpu_ir.index.verify import verify_index
    from tpu_ir.search import Scorer

    assert verify_index(index_dir)["ok"]
    for s in range(4):
        z1, z2 = fmt.load_shard(ref_dir, s), fmt.load_shard(index_dir, s)
        for key in ["term_ids", "indptr", "pair_doc", "pair_tf", "df"]:
            np.testing.assert_array_equal(z1[key], z2[key],
                                          err_msg=f"{s}/{key}")
        assert filecmp.cmp(os.path.join(ref_dir, positions_name(s)),
                           os.path.join(index_dir, positions_name(s)),
                           shallow=False), s
    for name in [fmt.DICTIONARY, fmt.DOCNOS, fmt.VOCAB]:
        assert (open(os.path.join(ref_dir, name), "rb").read()
                == open(os.path.join(index_dir, name), "rb").read()), name
    s_mh, s_ref = Scorer.load(index_dir), Scorer.load(ref_dir)
    for q in ["alpha", "charlie bravo", '"charlie delta"', "zulu"]:
        assert s_mh.search(q) == s_ref.search(q), q


def test_multihost_resume_after_pass2_crash(tmp_path):
    corpus_dir = write_corpus(tmp_path)
    index_dir = str(tmp_path / "mh_index")

    # run 1: process 1 dies on its SECOND device step (batch b=1); batch 0
    # finished on both processes, later batches did not
    run_workers(tmp_path, corpus_dir, index_dir,
                env_extra={"TEST_CRASH_STEP": "2", "TEST_CRASH_PID": "1"},
                expect_fail_pid=1)
    # process 1 died before its b=1 device step: exactly batch 0 complete
    # on it; process 0 (killed in the next collective) also holds batch 0
    n0, done0 = spill_batches(index_dir, 0)
    n1, done1 = spill_batches(index_dir, 1)
    assert n0 == 3 and n1 == 3, (n0, n1)
    assert done1 == [0], done1
    assert 0 in done0 and len(done0) < n0, done0

    # run 2: restart both. Tokenizing is FORBIDDEN for both processes;
    # the globally-complete batches are skipped in lockstep, the rest run
    expect_steps = 3 - len(set(done0) & set(done1))
    out = run_workers(
        tmp_path, corpus_dir, index_dir,
        env_extra={"TEST_FORBID_TOKENIZE": "0,1"})
    assert out[0]["num_docs"] == len(DOCS)
    assert out[0]["steps"] == expect_steps, (out, done0, done1)
    assert out[1]["steps"] == expect_steps, (out, done0, done1)
    assert expect_steps == 2, (done0, done1)

    assert_identical_to_reference(index_dir,
                                  build_reference(tmp_path, corpus_dir))
    # spills cleaned up after the successful finish
    assert not [n for n in os.listdir(index_dir) if n.startswith("_spill")]


def test_multihost_pass3_crash_writes_no_premature_metadata(tmp_path):
    """Metadata must only appear after EVERY process finished pass 3 (it
    is the skip-if-exists gate): process 1 dying in pass 3 while process
    0 has already written its parts must leave NO metadata.json, and the
    restart completes with ZERO device steps (all pass-2 spills valid)
    and resumed pass-3 parts."""
    corpus_dir = write_corpus(tmp_path)
    index_dir = str(tmp_path / "mh_index")

    run_workers(tmp_path, corpus_dir, index_dir,
                env_extra={"TEST_CRASH_PASS3_PID": "1"},
                expect_fail_pid=1)
    from tpu_ir.index import format as fmt

    # the barrier kept process 0 from certifying a half-finished index
    assert not os.path.exists(os.path.join(index_dir, fmt.METADATA))
    assert not os.path.exists(os.path.join(index_dir, fmt.part_name(2)))

    out = run_workers(tmp_path, corpus_dir, index_dir,
                      env_extra={"TEST_FORBID_TOKENIZE": "0,1"})
    assert out[0]["steps"] == 0 and out[1]["steps"] == 0, out
    assert_identical_to_reference(index_dir,
                                  build_reference(tmp_path, corpus_dir))


def test_multihost_lost_spills_forces_clean_pass2(tmp_path):
    """One process losing its local spill dir (disk wipe) invalidates
    EVERYONE's pass-2 state via the agreement allgather — the survivor
    still resumes its own pass-1 spills (no re-tokenize), but every batch
    recomputes and stale pass-3 outputs are discarded."""
    corpus_dir = write_corpus(tmp_path)
    index_dir = str(tmp_path / "mh_index")

    run_workers(tmp_path, corpus_dir, index_dir,
                env_extra={"TEST_CRASH_STEP": "2", "TEST_CRASH_PID": "1"},
                expect_fail_pid=1)
    # process 1 loses its spill dir; a stale garbage part for one of its
    # rows lingers in the shared dir and must be wiped, not trusted
    shutil.rmtree(os.path.join(index_dir, "_spill-p001"))
    from tpu_ir.index import format as fmt
    from tpu_ir.index.positions import positions_name

    with open(os.path.join(index_dir, fmt.part_name(2)), "wb") as f:
        f.write(b"garbage")
    with open(os.path.join(index_dir, positions_name(2)), "wb") as f:
        f.write(b"garbage")

    # restart: only process 0 may skip tokenizing; NO batch skips (the
    # agreement fails), so all lockstep device steps run on both
    out = run_workers(tmp_path, corpus_dir, index_dir,
                      env_extra={"TEST_FORBID_TOKENIZE": "0"})
    assert out[0]["steps"] == 3 and out[1]["steps"] == 3, out
    assert_identical_to_reference(index_dir,
                                  build_reference(tmp_path, corpus_dir))


def test_multihost_sigkill_and_resume(tmp_path):
    """KILL-and-resume (not exception-and-resume): process 1 takes a real
    SIGKILL mid-pass-2 — no unwinding, no atexit, exactly a preempted or
    OOM-killed host. The restart must not re-tokenize on either process,
    must skip the globally-complete batches, and must converge to
    artifacts byte-identical to the single-process streaming build."""
    corpus_dir = write_corpus(tmp_path)
    index_dir = str(tmp_path / "mh_index")

    run_workers(tmp_path, corpus_dir, index_dir,
                env_extra={"TEST_SIGKILL_STEP": "2", "TEST_SIGKILL_PID": "1"},
                expect_fail_pid=1, expect_signal=9)
    # the kill landed before process 1's b=1 device step: its batch-0
    # spills exist (atomic), nothing later does
    n1, done1 = spill_batches(index_dir, 1)
    assert n1 == 3 and done1 == [0], (n1, done1)

    out = run_workers(tmp_path, corpus_dir, index_dir,
                      env_extra={"TEST_FORBID_TOKENIZE": "0,1"})
    assert out[0]["num_docs"] == len(DOCS)
    # at least batch 0 was globally complete, so fewer than all 3 steps ran
    assert out[0]["steps"] == out[1]["steps"] < 3, out
    assert_identical_to_reference(index_dir,
                                  build_reference(tmp_path, corpus_dir))
    assert not [n for n in os.listdir(index_dir) if n.startswith("_spill")]


def test_multihost_corrupt_pair_spill_recomputes_batch(tmp_path):
    """A corrupt pair spill on one process flips that BATCH to not-done in
    the done-flag allgather, so every process recomputes it in lockstep —
    no raw BadZipFile, no whole-build restart."""
    corpus_dir = write_corpus(tmp_path)
    index_dir = str(tmp_path / "mh_index")

    run_workers(tmp_path, corpus_dir, index_dir,
                env_extra={"TEST_CRASH_STEP": "3", "TEST_CRASH_PID": "1"},
                expect_fail_pid=1)
    # batches 0 and 1 completed on process 1 before the crash
    n1, done1 = spill_batches(index_dir, 1)
    assert 0 in done1 and 1 in done1, done1
    # batch 0's spill for one of process 1's rows rots on disk
    victim = os.path.join(index_dir, "_spill-p001", "pairs-002-00000.npz")
    with open(victim, "r+b") as f:
        f.truncate(os.path.getsize(victim) // 2)

    # restart: no re-tokenize anywhere; batch 0 must RE-RUN (its corrupt
    # spill invalidated it globally) while batch 1 still skips
    out = run_workers(tmp_path, corpus_dir, index_dir,
                      env_extra={"TEST_FORBID_TOKENIZE": "0,1"})
    assert out[0]["steps"] == out[1]["steps"] == 2, out
    assert_identical_to_reference(index_dir,
                                  build_reference(tmp_path, corpus_dir))


def test_multihost_corrupt_manifest_rejected(tmp_path):
    """Garbage where one process's pass-1 manifest should be must be
    REJECTED: that process re-tokenizes its slice, the agreement
    allgather invalidates everyone's pass-2 state (global ids may have
    shifted), and the rebuild still converges byte-identically — never a
    traceback, never a trusted-garbage index."""
    corpus_dir = write_corpus(tmp_path)
    index_dir = str(tmp_path / "mh_index")

    run_workers(tmp_path, corpus_dir, index_dir,
                env_extra={"TEST_CRASH_STEP": "2", "TEST_CRASH_PID": "1"},
                expect_fail_pid=1)
    manifest = os.path.join(index_dir, "_spill-p001", "pass1.npz")
    assert os.path.exists(manifest)
    with open(manifest, "wb") as f:
        f.write(b"definitely not an npz manifest")

    # process 0's manifest is intact: it must NOT re-tokenize; process 1
    # must (its pass-1 state is gone). No pass-2 batch may be skipped —
    # a fresh pass-1 anywhere voids the global agreement.
    out = run_workers(tmp_path, corpus_dir, index_dir,
                      env_extra={"TEST_FORBID_TOKENIZE": "0"})
    assert out[0]["steps"] == 3 and out[1]["steps"] == 3, out
    assert_identical_to_reference(index_dir,
                                  build_reference(tmp_path, corpus_dir))
