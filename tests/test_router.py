"""Scatter-gather serving tier acceptance suite (ISSUE 10).

The contract: N doc-shard workers behind the router serve ONE logical
index — all-healthy merged results are BIT-identical to the
single-process Scorer (tie order included) across layouts × scorings;
a lost shard yields a tagged `partial` response that is a provably
correct subset; a SIGKILLed replica is invisible (failover); slow
replicas get hedged; and the whole taxonomy (full / degraded / partial
/ rejected) survives real multi-process chaos with conservation intact.
"""

import time

import numpy as np
import pytest

from tpu_ir.index.streaming import build_index_streaming
from tpu_ir.search import Scorer
from tpu_ir.search.layout import restrict_tiers, shard_doc_ranges
from tpu_ir.serving import (
    Overloaded,
    Router,
    RouterConfig,
    merge_shard_topk,
    run_distributed_soak,
    serve_worker,
)
from tpu_ir.obs.server import MetricsServer, health_snapshot

WORDS = ("salmon fishing river bears honey quick brown fox lazy dog "
         "market investor asset bond stock season rain forest".split())

N_SHARDS = 3


def write_corpus(path, n_docs=150):
    body = []
    for i in range(n_docs):
        text = " ".join(WORDS[(i + j) % len(WORDS)]
                        for j in range(3 + (i % 7)))
        body.append(f"<DOC>\n<DOCNO> D-{i:04d} </DOCNO>\n<TEXT>\n"
                    f"{text}\n</TEXT>\n</DOC>\n")
    path.write_text("".join(body))
    return str(path)


@pytest.fixture(scope="module")
def index_dir(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("router")
    corpus = write_corpus(tmp / "corpus.trec")
    out = str(tmp / "idx")
    build_index_streaming([corpus], out, k=1, num_shards=3,
                          batch_docs=40, chargram_ks=[])
    return out


@pytest.fixture(scope="module")
def ref_scorers(index_dir):
    """Single-process reference scorers per layout (the merge oracle)."""
    return {layout: Scorer.load(index_dir, layout=layout)
            for layout in ("sparse", "sharded")}


@pytest.fixture(scope="module")
def worker_scorers(index_dir, ref_scorers):
    """In-process doc-range-restricted worker scorers per layout —
    the merge property is about the SCORERS + merge function; the HTTP
    plumbing is exercised separately."""
    num_docs = ref_scorers["sparse"].meta.num_docs
    ranges = shard_doc_ranges(num_docs, N_SHARDS)
    return {layout: [Scorer.load(index_dir, layout=layout, doc_range=rg)
                     for rg in ranges]
            for layout in ("sparse", "sharded")}


QUERIES = ["salmon fishing", "bears honey market", "quick",
           "rain forest investor", "asset bond stock season",
           "dog dog salmon", "nosuchterm", "fox market rain"]


# ---------------------------------------------------------------------------
# partition + restriction units
# ---------------------------------------------------------------------------


def test_shard_doc_ranges_partition():
    ranges = shard_doc_ranges(10, 3)
    assert ranges == [(1, 4), (5, 8), (9, 10)]
    # disjoint cover of 1..D
    seen = [d for lo, hi in ranges for d in range(lo, hi + 1)]
    assert seen == list(range(1, 11))
    # more shards than docs: trailing shards own empty ranges
    ranges = shard_doc_ranges(3, 5)
    assert ranges[0] == (1, 1)
    assert all(hi < lo for lo, hi in ranges[3:])
    with pytest.raises(ValueError):
        shard_doc_ranges(10, 0)


def test_restrict_tiers_zeroes_only_out_of_range(ref_scorers, index_dir):
    from tpu_ir.search.layout import load_serving_cache

    meta = ref_scorers["sparse"].meta
    tiers, _df, _norms = load_serving_cache(index_dir, meta=meta)
    lo, hi = 10, 60
    masked = restrict_tiers(tiers, lo, hi)
    # geometry untouched — identical programs by construction
    assert masked.hot_rank is tiers.hot_rank
    assert masked.num_hot == tiers.num_hot
    assert all(a.shape == b.shape for a, b in
               zip(masked.tier_tfs, tiers.tier_tfs))
    for td, tt_old, tt_new in zip(tiers.tier_docs, tiers.tier_tfs,
                                  masked.tier_tfs):
        td = np.asarray(td).astype(np.int64)
        in_range = (td >= lo) & (td <= hi)
        np.testing.assert_array_equal(
            np.asarray(tt_new)[in_range], np.asarray(tt_old)[in_range])
        assert not np.asarray(tt_new)[~in_range].any()
    hd = np.asarray(tiers.hot_docs).astype(np.int64)
    in_range = (hd >= lo) & (hd <= hi)
    np.testing.assert_array_equal(np.asarray(masked.hot_vals)[in_range],
                                  np.asarray(tiers.hot_vals)[in_range])
    assert not np.asarray(masked.hot_vals)[~in_range].any()


def test_doc_range_validates(index_dir):
    with pytest.raises(ValueError):
        Scorer.load(index_dir, layout="sparse", doc_range=(0, 10))
    with pytest.raises(ValueError):
        Scorer.load(index_dir, layout="sparse", doc_range=(1, 10 ** 9))


# ---------------------------------------------------------------------------
# THE property: N-shard exact merge == single-index top-k, bit-exact
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("layout", ["sparse", "sharded"])
@pytest.mark.parametrize("scoring", ["tfidf", "bm25"])
def test_nshard_merge_bitexact(ref_scorers, worker_scorers, layout,
                               scoring):
    """All-healthy: merge of per-shard top-k == single-process top-k —
    full (docid, score) tuples, float bits and tie order included."""
    ref = ref_scorers[layout]
    workers = worker_scorers[layout]
    for q in QUERIES:
        full = list(ref.search_batch([q], k=10, scoring=scoring,
                                     return_docids=False)[0])
        shard_hits = [list(w.search_batch([q], k=10, scoring=scoring,
                                          return_docids=False)[0])
                      for w in workers]
        merged = [(int(d), float(s))
                  for d, s in merge_shard_topk(shard_hits, 10)]
        assert merged == full, (layout, scoring, q)


@pytest.mark.parametrize("layout", ["sparse", "sharded"])
@pytest.mark.parametrize("scoring", ["tfidf", "bm25"])
def test_nshard_merge_one_shard_lost(ref_scorers, worker_scorers,
                                     layout, scoring):
    """One shard lost: the merge of the SURVIVING shards equals the
    full ranking filtered to their doc ranges — the partial-subset
    correctness the router's `partial` tag promises."""
    ref = ref_scorers[layout]
    workers = worker_scorers[layout]
    num_docs = ref.meta.num_docs
    ranges = shard_doc_ranges(num_docs, N_SHARDS)
    for lost in range(N_SHARDS):
        ok_ranges = [rg for s, rg in enumerate(ranges) if s != lost]
        for q in QUERIES[:4]:
            # the independent oracle: the FULL positive ranking,
            # filtered to the surviving ranges
            rank = list(ref.search_batch([q], k=num_docs,
                                         scoring=scoring,
                                         return_docids=False)[0])
            expect = [(int(d), float(s)) for d, s in rank
                      if any(lo <= d <= hi for lo, hi in ok_ranges)][:10]
            shard_hits = [
                list(w.search_batch([q], k=10, scoring=scoring,
                                    return_docids=False)[0])
                for s, w in enumerate(workers) if s != lost]
            merged = [(int(d), float(s))
                      for d, s in merge_shard_topk(shard_hits, 10)]
            assert merged == expect, (layout, scoring, q, lost)


# ---------------------------------------------------------------------------
# the HTTP worker + router path (in-process workers, real sockets)
# ---------------------------------------------------------------------------


@pytest.fixture()
def http_workers(index_dir):
    """Three in-process HTTP workers (sparse layout, one replica each)
    + teardown. Function-scoped: the obs-server threads must start and
    stop within one test (the conftest thread-leak guard)."""
    started = []
    for s in range(N_SHARDS):
        srv, fe, sc = serve_worker(index_dir, s, N_SHARDS,
                                   layout="sparse", warm=False)
        started.append((srv, fe, sc))
    yield [[f"127.0.0.1:{srv.port}"] for srv, _, _ in started]
    for srv, _, _ in started:
        srv.stop()


def test_routed_search_bitexact_and_health(index_dir, ref_scorers,
                                           http_workers):
    ref = ref_scorers["sparse"]
    with Router(index_dir, http_workers,
                RouterConfig(deadline_ms=30000)) as router:
        for scoring in ("tfidf", "bm25"):
            for q in QUERIES[:4]:
                full = list(ref.search_batch([q], k=10,
                                             scoring=scoring)[0])
                res = router.search(q, k=10, scoring=scoring)
                assert Router.classify(res) == "full"
                assert res.shards_ok == tuple(range(N_SHARDS))
                assert not res.missing_shards
                assert list(res) == full, (scoring, q)
        # two-phase rerank: bit-identical to the single-process
        # rerank pipeline
        for q in QUERIES[:4]:
            full = list(ref.search_batch([q], k=10, rerank=25)[0])
            res = router.search(q, k=10, rerank=25)
            assert Router.classify(res) == "full"
            assert list(res) == full, q
        # phrase queries are not routable — loud, not silent
        with pytest.raises(ValueError):
            router.search('"salmon fishing"')
        # aggregated health: every replica up, worker identity present
        h = router.health_summary()
        assert h["num_shards"] == N_SHARDS
        for s, sh in enumerate(h["shards"]):
            assert sh["doc_range"][0] >= 1
            (rep,) = sh["replicas"]
            assert rep["up"] is True
            assert rep["worker"]["shard"] == s
            assert rep["worker"]["generation"] == 0
            assert rep["breaker"]["state"] == "closed"
        # the router rides the process /healthz via register_router
        snap = health_snapshot()
        assert snap["shards"]["num_shards"] == N_SHARDS
        # querylog: routed requests record their fan-out decision
        from tpu_ir.obs import querylog

        routed = [e for e in querylog.recent() if e.get("router")]
        assert routed
        assert routed[-1]["shards_ok"] == list(range(N_SHARDS))
        assert routed[-1]["partial"] is False


def test_routed_partial_and_failover(index_dir, ref_scorers,
                                     http_workers):
    """Kill shard 2's only replica -> responses ship partial with the
    healthy shards' exact subset; with a second replica present the
    same kill is invisible (failover)."""
    ref = ref_scorers["sparse"]
    num_docs = ref.meta.num_docs
    ranges = shard_doc_ranges(num_docs, N_SHARDS)
    # a dead address: bind-and-release a port so connects are refused
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()

    grid_partial = [http_workers[0], http_workers[1], [dead]]
    with Router(index_dir, grid_partial,
                RouterConfig(deadline_ms=30000)) as router:
        q = "salmon fishing"
        res = router.search(q, k=10, scoring="bm25")
        assert Router.classify(res) == "partial"
        assert res.missing_shards == (2,)
        assert res.shards_ok == (0, 1)
        rank = list(ref.search_batch([q], k=num_docs, scoring="bm25",
                                     return_docids=False)[0])
        expect = [(ref.mapping.get_docid(int(d)), float(s_))
                  for d, s_ in rank
                  if any(lo <= d <= hi
                         for lo, hi in ranges[:2])][:10]
        assert list(res) == expect

    # failover: same dead primary, but a live replica behind it
    grid_failover = [http_workers[0], http_workers[1],
                     [dead, http_workers[2][0]]]
    with Router(index_dir, grid_failover,
                RouterConfig(deadline_ms=30000)) as router:
        for q in QUERIES[:3]:
            full = list(ref.search_batch([q], k=10, scoring="bm25")[0])
            res = router.search(q, k=10, scoring="bm25")
            assert Router.classify(res) == "full", q
            assert list(res) == full


def test_all_shards_down_sheds_structurally(index_dir):
    import socket

    dead = []
    for _ in range(2):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead.append([f"127.0.0.1:{s.getsockname()[1]}"])
        s.close()
    with Router(index_dir, dead,
                RouterConfig(deadline_ms=2000)) as router:
        with pytest.raises(Overloaded) as ei:
            router.search("salmon", k=5)
        assert ei.value.reason == "no_healthy_shards"


# ---------------------------------------------------------------------------
# hedging + breakers (fake workers: handler behavior under our control)
# ---------------------------------------------------------------------------


def _fake_worker(hits, sleep_s=0.0):
    """A worker stub returning fixed hits after an optional delay."""

    calls = []

    def search(payload):
        calls.append(payload)
        if sleep_s:
            time.sleep(sleep_s)
        return {"hits": hits, "level": "full", "degraded": False}

    srv = MetricsServer(rpc_handlers={"search": search}).start()
    return srv, calls


def test_hedged_dispatch_beats_slow_replica(index_dir):
    slow_srv, slow_calls = _fake_worker([[1, 3.0]], sleep_s=1.5)
    fast_srv, fast_calls = _fake_worker([[1, 3.0]])
    try:
        with Router(index_dir, [[f"127.0.0.1:{slow_srv.port}",
                                 f"127.0.0.1:{fast_srv.port}"]],
                    RouterConfig(deadline_ms=10000,
                                 hedge_ms=60.0)) as router:
            from tpu_ir.obs import get_registry

            # force the slow replica to be the round-robin primary
            router._stats[0]._cursor = len(router._topology()[0]) - 1
            fired0 = get_registry().get("router.hedge_fired")
            t0 = time.perf_counter()
            res = router.search("whatever", k=5, return_docids=False)
            elapsed = time.perf_counter() - t0
            assert list(res) == [(1, 3.0)]
            assert get_registry().get("router.hedge_fired") == fired0 + 1
            assert res.hedges == 1
            # the hedge answered; the slow primary's 1.5 s never gated
            assert elapsed < 1.2
            assert fast_calls  # hedge actually reached the backup
    finally:
        slow_srv.stop()
        fast_srv.stop()


def test_none_placeholder_replica_slots_are_skipped(index_dir):
    """A static grid may carry None for unstaffed replica slots; the
    router must dial only addressed replicas, keeping grid-aligned
    replica numbering (regression: the order used filtered positions
    while dialing indexed the unfiltered row)."""
    srv, calls = _fake_worker([[5, 2.0]])
    try:
        with Router(index_dir, [[None, f"127.0.0.1:{srv.port}", None]],
                    RouterConfig(deadline_ms=5000)) as router:
            for _ in range(3):  # round-robin must never land on a None
                res = router.search("q", k=5, return_docids=False)
                assert Router.classify(res) == "full"
                assert list(res) == [(5, 2.0)]
            assert len(calls) == 3
    finally:
        srv.stop()


def test_replica_breaker_opens_and_probes(index_dir):
    """Consecutive replica failures open its breaker (fast-fail);
    a later success through the half-open probe closes it."""
    flaky_state = {"fail": True}

    def search(payload):
        if flaky_state["fail"]:
            raise RuntimeError("injected worker failure")
        return {"hits": [[2, 1.0]], "level": "full", "degraded": False}

    srv = MetricsServer(rpc_handlers={"search": search}).start()
    try:
        with Router(index_dir, [[f"127.0.0.1:{srv.port}"]],
                    RouterConfig(deadline_ms=2000, breaker_threshold=2,
                                 breaker_cooldown_s=0.1)) as router:
            for _ in range(3):
                with pytest.raises(Overloaded):
                    router.search("q", k=5)
            assert router._breaker(0, 0).state == "open"
            flaky_state["fail"] = False
            time.sleep(0.15)  # past the cooldown: next try is a probe
            res = router.search("q", k=5, return_docids=False)
            assert list(res) == [(2, 1.0)]
            assert router._breaker(0, 0).state == "closed"
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# adaptive batch ladder (ROADMAP 3 follow-up satellite)
# ---------------------------------------------------------------------------


def test_batch_ladder_adapts_to_cpu_backend(monkeypatch):
    from tpu_ir.serving import batch_ladder

    # unset: the CPU-class probe drops rungs above 16
    monkeypatch.delenv("TPU_IR_BATCH_LADDER", raising=False)
    assert batch_ladder() == (1, 4, 16)
    # explicit setting always wins over the probe
    monkeypatch.setenv("TPU_IR_BATCH_LADDER", "1,4,16,64")
    assert batch_ladder() == (1, 4, 16, 64)
    monkeypatch.setenv("TPU_IR_BATCH_LADDER", "2,8")
    assert batch_ladder() == (2, 8)


def test_batch_ladder_keeps_top_rung_on_rtt_backend(monkeypatch):
    import tpu_ir.search.scorer as scorer_mod
    from tpu_ir.serving import batch_ladder

    monkeypatch.delenv("TPU_IR_BATCH_LADDER", raising=False)
    monkeypatch.setattr(scorer_mod, "_rtt_dominated_backend",
                        lambda: True)
    assert batch_ladder() == (1, 4, 16, 64)


# ---------------------------------------------------------------------------
# bench-check: routed metrics are gated, direction-aware
# ---------------------------------------------------------------------------


def test_bench_check_gates_routed_metrics():
    from tpu_ir.obs.bench_check import METRICS, check_history

    for name in ("routed_qps", "routed_p99_ms", "partial_fraction",
                 "hedge_fired"):
        assert name in METRICS
    base = {"config": "serve_routed-100q-s2r2", "backend": "cpu",
            "routed_qps": 100.0, "routed_p99_ms": 80.0,
            "partial_fraction": 0.0, "hedge_fired": 2}
    rows = [dict(base) for _ in range(4)]
    # a collapse in routed throughput breaches (direction: higher)
    rows.append(dict(base, routed_qps=20.0))
    rep = check_history(rows, window=8, min_rows=3, tolerance=0.3)
    assert rep["status"] == "breach"
    assert [b["metric"] for b in rep["breaches"]] == ["routed_qps"]
    # a partial_fraction that was never seen before breaches (lower)
    rows[-1] = dict(base, partial_fraction=0.5)
    rep = check_history(rows, window=8, min_rows=3, tolerance=0.3)
    assert [b["metric"] for b in rep["breaches"]] == ["partial_fraction"]


def test_serve_bench_shards_arg_validation(index_dir):
    from tpu_ir.cli import main

    assert main(["serve-bench", index_dir, "--shards", "0"]) == 2
    assert main(["serve-bench", index_dir, "--shards", "2",
                 "--layout", "sharded"]) == 2


# ---------------------------------------------------------------------------
# THE acceptance: distributed chaos soak (real subprocesses, SIGKILL)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_distributed_chaos_soak(index_dir, tmp_path):
    """Tier-1 fast variant of the ISSUE 10 acceptance: 2 shards x 2
    replicas as real subprocesses; mid-soak a replica is SIGKILLed
    (failover must hide it), then a WHOLE shard (partial results must
    appear, each a pinned-correct subset), then everything respawns
    (recovery must close partial_fraction). Conservation and the
    response taxonomy hold throughout."""
    report = run_distributed_soak(
        str(index_dir), shards=2, replicas=2, threads=6, queries=100,
        seed=0, rundir=str(tmp_path / "run"),
        flight_dir=str(tmp_path / "flight"),
        # deflake (ISSUE 12 satellite): sized for PARALLEL CI, where
        # worker subprocesses share 2 cores with the rest of the suite.
        # A generous worker deadline keeps a slow-but-alive worker from
        # degrading mid-measurement (dead workers still fail at
        # connection-refused speed — loss detection is unaffected), the
        # router deadline/queue keep a descheduled shard from shedding
        # structurally, and the recovery window absorbs respawned
        # workers warming under load.
        worker_deadline_s=3.0,
        router_config=RouterConfig(deadline_ms=8000.0,
                                   max_concurrency=16, max_queue=128),
        recovery_timeout_s=120.0)
    # conservation: nothing vanishes, nothing breaks structure
    assert report["served"] + report["shed"] == report["submitted"]
    assert report["errors"] == 0, report["error_samples"]
    assert report["deadlocked"] == 0
    # the replica SIGKILL is (near-)invisible to callers: failover
    # answers them. A whole-fleet-momentarily-unreachable blip under
    # parallel-CI load may shed a FEW structurally (tagged, conserved)
    # — but never a meaningful fraction. Margin sized for a 2-core CI
    # box where the whole-shard kill can coincide with a descheduled
    # router (ISSUE 16 deflake): shed is conservation-tagged weather,
    # a LOST request (the line above) is the actual failure mode.
    assert report["shed"] <= max(4, report["submitted"] // 8), report
    # taxonomy: every served response classified exactly once
    assert sum(report["classes"].values()) == report["served"]
    # the whole-shard outage produced partial responses...
    assert report["classes"]["partial"] > 0
    assert report["partial_fraction"] > 0
    # ...and every checked one was a bit-exact healthy-shard subset
    assert report["partial_checked"] > 0
    assert report["partial_mismatches"] == 0
    # full responses are bit-identical to the single-process scorer
    assert report["classes"]["full"] > 0
    assert report["full_mismatches"] == 0
    # chaos actually happened: kills -> respawns (1 replica + 1 shard)
    assert report["router"]["router.worker_respawn"] >= 3
    # recovery: with the shard back, the topology serves full again
    assert report["recovery_full"] == report["recovery_probes"]
    # the routed latency section is present for the bench row
    assert report["latency"]["router.request"]["count"] > 0
    # distributed tracing (ISSUE 18): every served, dispatched response
    # joined exactly one stitched trace whose span population matches
    # its fan-out + hedge + cross-process shape; partial / degraded /
    # hedged traces (the tail rule's clientele) are never missing; and
    # the bookkeeping overhead meets the acceptance bounds (<=5% of a
    # mean request enabled, <=1% disabled)
    dt = report["disttrace"]
    assert dt["traced"] > 0
    assert dt["violations"] == 0, dt["violation_samples"]
    assert dt["tail_missing"] == 0
    assert dt["mean_spans"] >= 3  # root + per-shard attempts at least
    assert dt["overhead"]["enabled_overhead_fraction"] <= 0.05
    assert dt["overhead"]["disabled_overhead_fraction"] <= 0.01
    # the SLO tracker saw the run: every served/shed request recorded
    slo = report["slo"]
    assert slo["good"] + slo["bad"] >= report["served"]
