"""MaxScore pruning tests (ops/scoring.py::_hot_stage_pruned).

The tiered layout IS the MaxScore partition: hot-strip terms (highest df,
lowest idf) are the non-essential lists, cold tiers the essential ones.
Pruning must be RANK-SAFE — identical top-k, including tie-breaks — with
the pruned branch provably taken (tfidf_prune_diag), not just falling
back to the full matmul. The reference scores every posting of every
query term (IntDocVectorsForwardIndex.java:192-223); these tests pin the
algorithmic improvement's correctness contract.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_ir.ops.scoring import (
    MAXSCORE_CAND,
    _prune_applicable,
    bm25_topk_tiered,
    dense_doc_matrix,
    dense_tf_matrix,
    bm25_topk_dense,
    tfidf_prune_diag,
    tfidf_topk_dense,
    tfidf_topk_tiered,
)
from tpu_ir.search.layout import build_tiered_layout

NDOCS = 2 * MAXSCORE_CAND + 500  # wide enough that pruning is applicable


def _zipf_pairs(vocab=2500, ndocs=NDOCS, n_occ=120_000, seed=3):
    """Synthetic CSR postings columns in term-major order with a steep
    df distribution (a real hot/cold split)."""
    rng = np.random.default_rng(seed)
    p = 1.0 / np.arange(1, vocab + 1)
    p /= p.sum()
    t = rng.choice(vocab, n_occ, p=p).astype(np.int64)
    d = rng.integers(1, ndocs + 1, n_occ).astype(np.int64)
    key, tf = np.unique(t * (ndocs + 1) + d, return_counts=True)
    pair_term = (key // (ndocs + 1)).astype(np.int32)
    pair_doc = (key % (ndocs + 1)).astype(np.int32)
    pair_tf = tf.astype(np.int32)
    df = np.bincount(pair_term, minlength=vocab).astype(np.int32)
    return pair_term, pair_doc, pair_tf, df


@pytest.fixture(scope="module")
def layout():
    pair_term, pair_doc, pair_tf, df = _zipf_pairs()
    # budget for ~24 hot rows: a real strip, far from covering the vocab
    lay = build_tiered_layout(pair_doc, pair_tf, df, num_docs=NDOCS,
                              hot_budget=24 * (NDOCS + 1))
    args = (jnp.asarray(lay.hot_rank), lay.hot_device(),
            jnp.asarray(lay.tier_of), jnp.asarray(lay.row_of),
            tuple(jnp.asarray(a) for a in lay.tier_docs),
            tuple(jnp.asarray(a) for a in lay.tier_tfs))
    hot_max_tf = jnp.max(args[1], axis=1)
    return (pair_term, pair_doc, pair_tf, df), lay, args, hot_max_tf


def _queries(df, lay, *, safe: bool, seed=11):
    """Query batches by construction. `safe=True`: mid-df cold terms
    (enough postings to fill a top-k threshold, high idf -> high tau)
    alternating with the HOTTEST hot term (max df -> near-zero idf ->
    tiny upper bound, but a real nonzero contribution for the candidate
    gather to reproduce). `safe=False`: hot-only queries (no cold
    postings -> tau = 0 -> provably unsafe)."""
    hot = np.nonzero(lay.hot_rank >= 0)[0]
    hottest = int(hot[np.argmax(df[hot])])
    cold_mid = np.nonzero((lay.hot_rank < 0) & (df >= 30) & (df <= 200))[0]
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(12):
        if safe and i % 2 == 0:
            rows.append([int(rng.choice(cold_mid)),
                         int(rng.choice(cold_mid)), -1])
        elif safe:
            rows.append([hottest, int(rng.choice(cold_mid)),
                         int(rng.choice(cold_mid))])
        elif i % 3 == 0:
            rows.append([int(rng.choice(hot)), int(rng.choice(hot)), -1])
        else:
            rows.append([int(rng.choice(hot)), int(rng.choice(cold_mid)),
                         int(rng.choice(cold_mid))])
    return np.array(rows, np.int32)


def test_prune_applicability_gate():
    assert _prune_applicable(10, NDOCS, True)
    assert not _prune_applicable(10, NDOCS, False)
    assert not _prune_applicable(MAXSCORE_CAND, NDOCS, True)  # k too big
    assert not _prune_applicable(10, 1000, True)  # doc axis too narrow


def test_tfidf_pruned_branch_engages_and_matches(layout):
    """On an all-cold-safe batch the diag must certify every query (the
    block takes the pruned branch) and the results must equal both the
    unpruned kernel and the dense oracle — docnos exactly."""
    (pt, pd, ptf, df), lay, args, hot_max_tf = layout
    q = _queries(df, lay, safe=True)
    safe = np.asarray(tfidf_prune_diag(
        jnp.asarray(q), *args, jnp.asarray(df), jnp.int32(NDOCS),
        hot_max_tf, num_docs=NDOCS, k=10))
    assert safe.all(), "constructed-safe batch must engage the pruned branch"

    s1, d1 = tfidf_topk_tiered(jnp.asarray(q), *args, jnp.asarray(df),
                               jnp.int32(NDOCS), hot_max_tf,
                               num_docs=NDOCS, k=10, prune=True)
    s0, d0 = tfidf_topk_tiered(jnp.asarray(q), *args, jnp.asarray(df),
                               jnp.int32(NDOCS), num_docs=NDOCS, k=10,
                               prune=False)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s0), rtol=1e-5)

    mat = dense_doc_matrix(jnp.asarray(pt), jnp.asarray(pd),
                           jnp.asarray(ptf), vocab_size=len(df),
                           num_docs=NDOCS)
    s2, d2 = tfidf_topk_dense(jnp.asarray(q), mat, jnp.asarray(df),
                              jnp.int32(NDOCS), k=10)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))


def test_tfidf_mixed_unsafe_batch_still_exact(layout):
    """A batch with hot-only queries (tau = 0 -> unsafe) must fall back
    to the full matmul and stay exact."""
    (pt, pd, ptf, df), lay, args, hot_max_tf = layout
    q = _queries(df, lay, safe=False)
    safe = np.asarray(tfidf_prune_diag(
        jnp.asarray(q), *args, jnp.asarray(df), jnp.int32(NDOCS),
        hot_max_tf, num_docs=NDOCS, k=10))
    assert not safe.all(), "hot-only queries must be flagged unsafe"

    s1, d1 = tfidf_topk_tiered(jnp.asarray(q), *args, jnp.asarray(df),
                               jnp.int32(NDOCS), hot_max_tf,
                               num_docs=NDOCS, k=10, prune=True)
    mat = dense_doc_matrix(jnp.asarray(pt), jnp.asarray(pd),
                           jnp.asarray(ptf), vocab_size=len(df),
                           num_docs=NDOCS)
    s2, d2 = tfidf_topk_dense(jnp.asarray(q), mat, jnp.asarray(df),
                              jnp.int32(NDOCS), k=10)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-4)


def test_bm25_pruned_matches_dense(layout):
    """BM25 pruning parity on safe and unsafe batches (its upper bound
    uses the saturation curve at max tf and min length norm)."""
    (pt, pd, ptf, df), lay, args, hot_max_tf = layout
    rng = np.random.default_rng(5)
    doc_len = np.zeros(NDOCS + 1, np.int32)
    doc_len[1:] = rng.integers(20, 200, NDOCS)
    tf_mat = dense_tf_matrix(jnp.asarray(pt), jnp.asarray(pd),
                             jnp.asarray(ptf), vocab_size=len(df),
                             num_docs=NDOCS)
    for safe in (True, False):
        q = _queries(df, lay, safe=safe)
        s1, d1 = bm25_topk_tiered(jnp.asarray(q), *args, jnp.asarray(df),
                                  jnp.asarray(doc_len), jnp.int32(NDOCS),
                                  hot_max_tf, num_docs=NDOCS, k=10,
                                  prune=True)
        s2, d2 = bm25_topk_dense(jnp.asarray(q), tf_mat, jnp.asarray(df),
                                 jnp.asarray(doc_len), jnp.int32(NDOCS),
                                 k=10)
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                                   rtol=1e-4)


def test_bm25_upper_bound_is_valid(layout):
    """The per-hot-row BM25 bound sat(max_tf, dl_min) must dominate every
    actual per-doc saturation value — the safety proof's premise."""
    (pt, pd, ptf, df), lay, args, hot_max_tf = layout
    rng = np.random.default_rng(9)
    doc_len = np.zeros(NDOCS + 1, np.float64)
    doc_len[1:] = rng.integers(20, 200, NDOCS)
    k1, b = 0.9, 0.4
    avg = doc_len.sum() / NDOCS
    dl_norm = 1.0 - b + b * doc_len / avg
    strip = np.asarray(args[1])  # [H, D+1]
    sat = strip * (k1 + 1.0) / (strip + k1 * dl_norm[None, :])
    actual_max = sat[:, 1:].max(axis=1)
    mtf = np.asarray(hot_max_tf, np.float64)
    bound = mtf * (k1 + 1.0) / (mtf + k1 * dl_norm[1:].min())
    # the kernel applies a 1e-4 relative safety margin on top of the bound
    # for exactly this: f32 rounding can put the bound an ulp below the
    # value it mathematically dominates
    assert (bound * 1.0001 + 1e-6 >= actual_max).all()


def test_scorer_wiring_prune_toggle(tmp_path):
    """Scorer-level wiring: prune on/off yield identical search results
    through the full pipeline (tiny corpus -> pruning statically gated
    off, but the prune=True kernels and hot_max_tf plumbing run), and
    prune_diag reports the engagement fields on the tiered layout."""
    from tpu_ir.index import build_index
    from tpu_ir.search import Scorer

    rng = np.random.default_rng(2)
    words = ["".join(rng.choice(list("abcdefghij"), 6)) for _ in range(300)]
    corpus = tmp_path / "c.trec"
    with open(corpus, "w") as f:
        for i in range(120):
            body = " ".join(rng.choice(words, 30))
            f.write(f"<DOC>\n<DOCNO> D-{i:04d} </DOCNO>\n<TEXT>\n{body}\n"
                    f"</TEXT>\n</DOC>\n")
    out = str(tmp_path / "idx")
    build_index([str(corpus)], out, k=1, chargram_ks=[], num_shards=2)

    s_on = Scorer.load(out, layout="sparse", prune=True)
    s_off = Scorer.load(out, layout="sparse", prune=False)
    # force multi-block dispatch through the prune scheduler (hot-free
    # queries packed first, results restored to caller order)
    s_on.SCORE_BUDGET = (121) * 3
    texts = [" ".join(rng.choice(words, 2)) for _ in range(16)]
    for scoring in ("tfidf", "bm25"):
        r_on = s_on.search_batch(texts, k=5, scoring=scoring)
        r_off = s_off.search_batch(texts, k=5, scoring=scoring)
        assert [[d for d, _ in r] for r in r_on] \
            == [[d for d, _ in r] for r in r_off]
    q = s_on.analyze_queries(texts)
    # the scheduled-skip diag works at any scale (the static cold-only
    # kernel is exact regardless of corpus size)
    diag = s_on.prune_diag(q)
    assert set(diag) >= {"prune_hot_free_query_fraction",
                         "prune_skip_block_fraction"}
    assert s_off.prune_diag(q) == {"prune_applicable": False}


def _make_scorer(layout_fixture, *, prune: bool, score_budget: int):
    """Minimal Scorer over the module's synthetic layout (large enough
    for _prune_applicable), bypassing index files — exactly the attrs
    topk()/_topk_device()/prune_diag() touch."""
    from tpu_ir.search.scorer import Scorer

    (pt, pd, ptf, df), lay, args, hot_max_tf = layout_fixture
    s = object.__new__(Scorer)
    s.layout = "sparse"
    s.prune = prune
    s.compat_int_idf = False
    s.SCORE_BUDGET = score_budget

    class M:
        num_docs = NDOCS
        vocab_size = len(df)

    s.meta = M()
    (s.hot_rank, s.hot_tfs, s.tier_of, s.row_of,
     s.tier_docs, s.tier_tfs) = args
    s.df = jnp.asarray(df)
    return s


def test_topk_reorder_restores_caller_order(layout):
    """Multi-block grouped dispatch: the scheduler routes hot-free
    queries to the static cold-only kernel and the rest to the full
    kernel, and the results MUST come back in caller order — compare
    against the unpruned scorer row by row on a batch interleaving
    hot-heavy and cold queries. Hot-free queries get IDENTICAL floats
    (the hot stage contributes exactly zero for them)."""
    (pt, pd, ptf, df), lay, args, hot_max_tf = layout

    s_on = _make_scorer(layout, prune=True, score_budget=(NDOCS + 1) * 4)
    s_off = _make_scorer(layout, prune=False,
                         score_budget=(NDOCS + 1) * 1000)
    # hot-free rows: cold mid-df pairs; hot rows: from the unsafe set
    # (batch large enough that the hot-free group exceeds MIN_SKIP_GROUP)
    cold_mid = np.nonzero((lay.hot_rank < 0) & (df >= 30) & (df <= 200))[0]
    rng = np.random.default_rng(3)
    q = np.empty((96, 3), np.int32)
    hot = np.nonzero(lay.hot_rank >= 0)[0]
    for i in range(0, 96, 2):
        q[i] = [int(rng.choice(hot)), int(rng.choice(cold_mid)), -1]
    for i in range(1, 96, 2):
        q[i] = [int(rng.choice(cold_mid)), int(rng.choice(cold_mid)), -1]
    s1, d1 = s_on.topk(q, k=10)
    s0, d0 = s_off.topk(q, k=10)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))
    # ulp-level: XLA compiles different reduction trees per block shape
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s0), rtol=1e-6)
    # the schedule really did reorder: hot-free queries come first
    order = s_on._prune_schedule(q)
    assert not np.array_equal(order, np.arange(len(q)))

    diag = s_on.prune_diag(q)
    assert 0.0 < diag["prune_skip_block_fraction"] < 1.0


def test_group_dispatch_shapes_are_content_independent(layout):
    """Schedule-group sizes are CONTENT-dependent (how many queries were
    hot-free), so _group_dispatch must only ever dispatch a closed set
    of shapes — pow2 buckets below the block size and the block size
    itself. A raw group-sized dispatch (e.g. 40 rows at block=48) would
    mint a fresh XLA compile per distinct query mix."""
    s_on = _make_scorer(layout, prune=True, score_budget=(NDOCS + 1) * 48)
    s_off = _make_scorer(layout, prune=False,
                         score_budget=(NDOCS + 1) * 1000)
    block = s_on._block_size()
    assert block == 48
    cold_mid = np.nonzero(
        (np.asarray(s_on.hot_rank) < 0)
        & (np.asarray(s_on.df) >= 30) & (np.asarray(s_on.df) <= 200))[0]
    hot = np.nonzero(np.asarray(s_on.hot_rank) >= 0)[0]
    rng = np.random.default_rng(7)
    # 40 hot-free + 20 hot: both groups land strictly between block/2
    # and block (40) or at a pow2 bucket (20 -> 32)
    q = np.empty((60, 3), np.int32)
    for i in range(40):
        q[i] = [int(rng.choice(cold_mid)), int(rng.choice(cold_mid)), -1]
    for i in range(40, 60):
        q[i] = [int(rng.choice(hot)), int(rng.choice(cold_mid)), -1]
    q = q[rng.permutation(60)]

    shapes = []
    orig = s_on._topk_device

    def spy(qb, *a, **kw):
        shapes.append(len(qb))
        return orig(qb, *a, **kw)

    s_on._topk_device = spy
    s1, d1 = s_on.topk(q, k=10)
    s0, d0 = s_off.topk(q, k=10)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s0), rtol=1e-6)
    allowed = {block} | {1 << e for e in range(block.bit_length())
                         if (1 << e) < block}
    assert shapes and set(shapes) <= allowed, (shapes, allowed)
    # the hot-free group (40 rows) was padded to the full block, not
    # dispatched raw
    assert 40 not in shapes


def test_skip_hot_kernel_exact(layout):
    """The static cold-only kernel (skip_hot) must produce bit-identical
    scores to the full kernel for hot-free queries — the hot stage
    contributes exactly zero for them."""
    (pt, pd, ptf, df), lay, args, hot_max_tf = layout
    cold_mid = np.nonzero((lay.hot_rank < 0) & (df >= 30) & (df <= 200))[0]
    rng = np.random.default_rng(8)
    q = rng.choice(cold_mid, size=(8, 3)).astype(np.int32)
    s1, d1 = tfidf_topk_tiered(jnp.asarray(q), *args, jnp.asarray(df),
                               jnp.int32(NDOCS), num_docs=NDOCS, k=10,
                               skip_hot=True)
    s0, d0 = tfidf_topk_tiered(jnp.asarray(q), *args, jnp.asarray(df),
                               jnp.int32(NDOCS), num_docs=NDOCS, k=10)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s0))
    rng2 = np.random.default_rng(9)
    doc_len = np.zeros(NDOCS + 1, np.int32)
    doc_len[1:] = rng2.integers(20, 200, NDOCS)
    s1, d1 = bm25_topk_tiered(jnp.asarray(q), *args, jnp.asarray(df),
                              jnp.asarray(doc_len), jnp.int32(NDOCS),
                              num_docs=NDOCS, k=10, skip_hot=True)
    s0, d0 = bm25_topk_tiered(jnp.asarray(q), *args, jnp.asarray(df),
                              jnp.asarray(doc_len), jnp.int32(NDOCS),
                              num_docs=NDOCS, k=10)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s0))


def test_exact_tie_order_preserved(layout):
    """Two docs with identical postings for the query terms must keep the
    same (lowest-docno-first) tie order under the pruned branch — the
    msmarco norm-tie queries depend on this."""
    (pt, pd, ptf, df), lay, args, hot_max_tf = layout
    # synthesize: find a mid-df cold term, take two docs that BOTH carry
    # it at the same tf, query just that term plus another safe filler
    cold_mid = np.nonzero((lay.hot_rank < 0) & (df >= 50) & (df <= 300))[0]
    indptr = np.concatenate([[0], np.cumsum(df, dtype=np.int64)])
    pick = None
    for tid in cold_mid:
        run_tf = ptf[indptr[tid]:indptr[tid + 1]]
        run_dn = pd[indptr[tid]:indptr[tid + 1]]
        vals, counts = np.unique(run_tf, return_counts=True)
        dup = vals[counts >= 2]
        if len(dup):
            docs = np.sort(run_dn[run_tf == dup[-1]])[:2]
            pick = (int(tid), docs)
            break
    assert pick is not None
    tid, docs = pick
    q = np.array([[tid, -1, -1]], np.int32)
    for prune in (True, False):
        kw = dict(num_docs=NDOCS, k=int(df[tid]), prune=prune)
        s, d = tfidf_topk_tiered(jnp.asarray(q), *args, jnp.asarray(df),
                                 jnp.int32(NDOCS), hot_max_tf, **kw)
        d = np.asarray(d)[0]
        i0, i1 = (np.nonzero(d == docs[0])[0][0],
                  np.nonzero(d == docs[1])[0][0])
        assert i0 < i1, "tie must break toward the lower docno"
