"""Acceptance suite for the device-cost profiling layer (ISSUE 7).

Pins the five contracts:

- the shape ladder: a profiled jit compiles exactly once per abstract
  signature — N distinct shapes = N compiles, a repeated shape adds
  ZERO — with per-executable cost_analysis FLOPs/bytes captured;
- recompiles are detected (same signature compiling again — the
  fresh-jit-per-call failure mode), counted, surfaced in the /healthz
  60 s window, and a storm past the limit writes a flight record;
- the Gauge primitive: snapshot/reset presence semantics, last-wins vs
  max merge policies (deterministic under permutation), Prometheus
  exposition;
- the scorer's dispatch span subdivides on the CPU backend:
  dispatch.device on every dispatch, dispatch.trace/dispatch.compile
  when a kernel call compiled, and a memory-gauge sample per dispatch;
- `tpu-ir bench-check`: pass / breach / insufficient-history exit
  codes on synthetic histories, direction-aware thresholds with noise
  floors, and the tier-1 `--self-test` that skips cleanly on the young
  checked-in history.
"""

from __future__ import annotations

import json
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from tpu_ir import obs
from tpu_ir.cli import main as cli_main
from tpu_ir.obs import aggregate, profiling
from tpu_ir.obs.profiling import profiled_jit
from tpu_ir.obs.registry import TelemetryRegistry


@pytest.fixture(autouse=True)
def _profiling_defaults():
    """Tests below flip the runtime profiling knobs; restore defaults
    (the ledger itself is cleared by conftest's telemetry fixture)."""
    yield
    profiling.configure(enabled=True, cost=True, recompile_limit=3)
    obs.configure(enabled=True)


# ---------------------------------------------------------------------------
# the shape ladder
# ---------------------------------------------------------------------------


def test_shape_ladder_compiles_exactly_once_per_signature():
    f = profiled_jit(lambda x: x * 2.0, label="ladder_fn")
    for n in (4, 8, 16):
        f(np.zeros(n, np.float32))
    reg = obs.get_registry()
    assert reg.get("compile.count") == 3
    assert reg.get("compile.recompiles") == 0
    rep = profiling.profile_report()
    fn = next(r for r in rep["functions"] if r["name"] == "ladder_fn")
    assert fn["compiles"] == 3
    assert len(fn["signatures"]) == 3
    assert all(s["compiles"] == 1 for s in fn["signatures"])
    # the compile wall landed in the histogram too
    assert reg.histogram("compile.time").count == 3
    # a REPEATED shape adds zero compiles anywhere
    f(np.zeros(8, np.float32))
    f(np.zeros(16, np.float32))
    assert reg.get("compile.count") == 3
    rep2 = profiling.profile_report()
    fn2 = next(r for r in rep2["functions"] if r["name"] == "ladder_fn")
    assert fn2["compiles"] == 3


def test_static_arg_change_is_a_new_signature():
    f = profiled_jit(lambda x, n: x * n, label="static_fn",
                     static_argnames=("n",))
    x = np.zeros(4, np.float32)
    f(x, n=2)
    f(x, n=3)
    f(x, n=2)  # cached
    rep = profiling.profile_report()
    fn = next(r for r in rep["functions"] if r["name"] == "static_fn")
    assert fn["compiles"] == 2
    assert len(fn["signatures"]) == 2
    assert {"n=2", "n=3"} == {
        s["signature"].split(", ")[-1] for s in fn["signatures"]}


def test_cost_analysis_flops_and_bytes_captured():
    f = profiled_jit(lambda x: (x * 2.0).sum(), label="cost_fn")
    f(np.zeros(64, np.float32))
    rep = profiling.profile_report()
    sig = next(r for r in rep["functions"]
               if r["name"] == "cost_fn")["signatures"][0]
    assert sig["flops"] is not None and sig["flops"] > 0
    assert sig["bytes_accessed"] is not None and sig["bytes_accessed"] > 0
    assert sig["last_compile_s"] > 0


def test_profile_disabled_is_a_passthrough():
    profiling.configure(enabled=False)
    f = profiled_jit(lambda x: x + 1.0, label="disabled_fn")
    out = f(np.zeros(4, np.float32))
    assert np.asarray(out).shape == (4,)
    assert obs.get_registry().get("compile.count") == 0
    assert profiling.profile_report()["functions"] == []


# ---------------------------------------------------------------------------
# recompile detection + storms
# ---------------------------------------------------------------------------


def test_recompile_storm_counts_window_and_flight_record(
        tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_IR_FLIGHT_DIR", str(tmp_path))
    profiling.configure(recompile_limit=2)
    # the classic failure mode: a fresh jit per call — one signature,
    # compiled over and over
    for _ in range(4):
        f = profiled_jit(lambda x: x + 1.0, label="storm_fn")
        f(np.zeros(4, np.float32))
    reg = obs.get_registry()
    assert reg.get("compile.count") == 4
    assert reg.get("compile.recompiles") == 3
    assert profiling.recompiles_last_60s() == 3
    rep = profiling.profile_report()
    fn = next(r for r in rep["functions"] if r["name"] == "storm_fn")
    assert fn["recompiles"] == 3
    assert len(fn["signatures"]) == 1
    # compiles 3 and 4 exceeded the limit of 2 -> storm record (the
    # recorder's per-reason rate limit collapses them into one file)
    records = list(tmp_path.glob("flight-*recompile_storm*.jsonl"))
    assert records, "no recompile_storm flight record written"
    header = json.loads(records[0].read_text().splitlines()[0])
    assert header["reason"] == "recompile_storm"
    assert header["extra"]["fn"] == "storm_fn"
    assert header["compile_cache"]["recompiles"] >= 2
    assert "memory" in header and header["memory"]["host_rss_bytes"] > 0


def test_healthy_repeated_calls_keep_recompile_window_zero():
    f = profiled_jit(lambda x: x * 3.0, label="healthy_fn")
    for _ in range(5):
        f(np.zeros(8, np.float32))
    assert profiling.recompiles_last_60s() == 0
    assert obs.get_registry().get("compile.recompiles") == 0


# ---------------------------------------------------------------------------
# gauges: snapshot / merge / exposition
# ---------------------------------------------------------------------------


def test_gauge_set_max_snapshot_and_reset():
    reg = TelemetryRegistry()
    reg.set_gauge("device.bytes_in_use", 100.0)
    reg.update_gauge_max("device.peak_bytes", 500.0)
    reg.update_gauge_max("device.peak_bytes", 300.0)   # peak never walks back
    snap = reg.snapshot()
    assert snap["gauges"]["device.bytes_in_use"] == 100.0
    assert snap["gauges"]["device.peak_bytes"] == 500.0
    # declared gauges are PRESENT at 0 before any sample (the contract)
    assert snap["gauges"]["host.rss_bytes"] == 0.0
    reg.set_gauge("custom.level", 7.0)
    reg.reset()
    snap2 = reg.snapshot()
    assert snap2["gauges"]["device.peak_bytes"] == 0.0   # declared: kept at 0
    assert "custom.level" not in snap2["gauges"]          # undeclared: dropped


def test_gauge_merge_last_wins_and_max_policies_permutation_invariant():
    a = TelemetryRegistry()
    b = TelemetryRegistry()
    a.set_gauge("device.bytes_in_use", 100.0)
    a.update_gauge_max("device.peak_bytes", 900.0)
    b.set_gauge("device.bytes_in_use", 250.0)
    b.update_gauge_max("device.peak_bytes", 400.0)
    sa, sb = a.collect_state(), b.collect_state()
    sa["time"], sb["time"] = "2026-01-01T00:00:00", "2026-01-02T00:00:00"
    for snaps in ([sa, sb], [sb, sa]):   # permutation invariant
        merged = aggregate.merge_snapshots(snaps)
        # "last": the NEWER snapshot's level wins regardless of order
        assert merged["gauges"]["device.bytes_in_use"] == 250.0
        # "max": the cluster-wide peak survives
        assert merged["gauges"]["device.peak_bytes"] == 900.0
    # snapshots without a gauges section (pre-ISSUE-7 spools) merge fine
    del sa["gauges"]
    merged = aggregate.merge_snapshots([sa, sb])
    assert merged["gauges"]["device.peak_bytes"] == 400.0


def test_warm_calls_racing_a_compiling_thread_record_no_recompile():
    """Compile detection is thread-local (monitoring events fire on the
    compiling thread): a warm-signature call racing another thread's
    compiles must never be misattributed as a recompile — the false
    recompile_storm that a process-global cache-size delta would
    produce under concurrent serving."""
    import threading

    f = profiled_jit(lambda x: x * 2.0, label="race_fn")
    warm = np.zeros(4, np.float32)
    f(warm)  # compile the warm signature up front
    stop = threading.Event()

    def churn():
        n = 5
        while not stop.is_set():
            f(np.zeros(n, np.float32))  # a fresh shape: compiles
            n += 1

    t = threading.Thread(target=churn)
    t.start()
    try:
        for _ in range(300):
            f(warm)
    finally:
        stop.set()
        t.join()
    assert obs.get_registry().get("compile.recompiles") == 0


def test_idle_process_gauges_do_not_zero_the_cluster_merge():
    # a process that never sampled memory serializes NO gauges, so its
    # (newer) snapshot cannot last-wins-zero real levels in the merge
    live = TelemetryRegistry()
    live.set_gauge("device.bytes_in_use", 777.0)
    idle = TelemetryRegistry()
    s_live, s_idle = live.collect_state(), idle.collect_state()
    assert s_idle["gauges"] == {}
    s_live["time"], s_idle["time"] = ("2026-01-01T00:00:00",
                                      "2026-01-02T00:00:00")  # idle newest
    merged = aggregate.merge_snapshots([s_live, s_idle])
    assert merged["gauges"]["device.bytes_in_use"] == 777.0
    # the LOCAL snapshot keeps the presence-at-0 contract regardless
    assert idle.snapshot()["gauges"]["device.bytes_in_use"] == 0.0


def test_gauge_prometheus_exposition():
    reg = TelemetryRegistry()
    reg.set_gauge("host.rss_bytes", 12345.0)
    text = reg.prometheus_text()
    assert "# TYPE tpu_ir_gauge gauge" in text
    assert 'tpu_ir_gauge{name="host.rss_bytes"} 12345.0' in text


# ---------------------------------------------------------------------------
# the dispatch split on a real scorer (CPU backend)
# ---------------------------------------------------------------------------

WORDS = ("salmon fishing river bears honey quick brown fox lazy dog "
         "market investor asset bond stock season rain forest".split())


@pytest.fixture(scope="module")
def scorer_index(tmp_path_factory):
    from tpu_ir.index import build_index

    tmp = tmp_path_factory.mktemp("profiling")
    body = []
    for i in range(60):
        text = " ".join(WORDS[(i + j) % len(WORDS)]
                        for j in range(3 + (i % 5)))
        body.append(f"<DOC>\n<DOCNO> D-{i:04d} </DOCNO>\n<TEXT>\n"
                    f"{text}\n</TEXT>\n</DOC>\n")
    corpus = tmp / "corpus.trec"
    corpus.write_text("".join(body))
    out = str(tmp / "idx")
    build_index([str(corpus)], out, k=1, num_shards=2, chargram_ks=[])
    return out


def test_dispatch_span_subdivides_and_samples_memory(scorer_index):
    from tpu_ir.search import Scorer

    scorer = Scorer.load(scorer_index, layout="sparse")
    q = scorer.analyze_queries(["salmon fishing"])
    obs.clear_traces()
    scorer.topk(q, k=5, scoring="tfidf")
    disp = [t for c in obs.recent_traces() for t in [c]
            if t.name == "dispatch"][-1]
    names = [c.name for c in disp.children]
    # every dispatch carries the device-completion wait
    assert "dispatch.device" in names
    kernel = next(c for c in disp.children if c.name == "kernel")
    reg = obs.get_registry()
    assert reg.histogram("dispatch.device").count >= 1
    if reg.get("compile.count"):
        # a cold kernel call: the split sub-spans ride inside the tree
        sub = [c.name for c in kernel.children]
        assert "dispatch.compile" in sub
    # the per-dispatch memory sample landed (host RSS always available)
    assert reg.get_gauge("host.rss_bytes") > 0
    assert reg.get_gauge("host.peak_rss_bytes") >= \
        reg.get_gauge("host.rss_bytes")
    # repeat dispatch at the same shape: no new compiles
    before = reg.get("compile.count")
    scorer.topk(q, k=5, scoring="tfidf")
    assert reg.get("compile.count") == before


# ---------------------------------------------------------------------------
# the report surfaces: CLI, /profile, /healthz
# ---------------------------------------------------------------------------


def test_profile_cli_reports_functions_and_split(capsys):
    f = profiled_jit(lambda x: x - 1.0, label="cli_fn")
    f(np.zeros(4, np.float32))
    assert cli_main(["profile"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["enabled"] is True
    names = [fn["name"] for fn in out["functions"]]
    assert "cli_fn" in names
    fn = out["functions"][names.index("cli_fn")]
    assert fn["signatures"][0]["signature"] == "float32[4]"
    assert "dispatch.device" in out["dispatch"]
    assert "compile.time" in out["dispatch"]
    assert "gauges" in out and "recompiles_last_60s" in out


def test_profile_endpoint_and_healthz_window():
    from tpu_ir.obs.server import MetricsServer

    f = profiled_jit(lambda x: x * 5.0, label="http_fn")
    f(np.zeros(4, np.float32))
    with MetricsServer(port=0) as srv:
        with urllib.request.urlopen(srv.url + "/profile",
                                    timeout=10) as r:
            prof = json.loads(r.read())
        assert any(fn["name"] == "http_fn" for fn in prof["functions"])
        assert prof["compile_counters"]["compile.count"] >= 1
        with urllib.request.urlopen(srv.url + "/healthz",
                                    timeout=10) as r:
            health = json.loads(r.read())
        assert health["recompiles_last_60s"] == 0
        # the root index advertises the new endpoint
        with urllib.request.urlopen(srv.url + "/", timeout=10) as r:
            assert "/profile" in json.loads(r.read())["endpoints"]


def test_flight_header_carries_memory_and_compile_cache():
    from tpu_ir.obs.recorder import artifact_lines

    f = profiled_jit(lambda x: x / 2.0, label="flight_fn")
    f(np.zeros(4, np.float32))
    header = json.loads(artifact_lines("unit_test")[0])
    assert header["memory"]["host_rss_bytes"] > 0
    assert header["compile_cache"]["compiles"] >= 1
    assert header["compile_cache"]["functions"] >= 1


# ---------------------------------------------------------------------------
# bench-check: the regression sentry
# ---------------------------------------------------------------------------


def _history(path: Path, rows: list) -> str:
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    return str(path)


def _rows(n: int, **last_overrides) -> list:
    base = {"config": "ref", "backend": "cpu", "metric":
            "docs_per_sec_indexed", "value": 300.0, "queries_per_sec":
            50_000.0, "query_p50_ms": 10.0, "scorer_load_cold_s": 5.0,
            "compile_s": 20.0, "recompiles": 0, "peak_hbm_bytes": -1}
    rows = [dict(base, value=300.0 + i) for i in range(n)]
    rows[-1].update(last_overrides)
    return rows


def test_bench_check_pass_exit_zero(tmp_path, capsys):
    p = _history(tmp_path / "h.jsonl", _rows(6))
    assert cli_main(["bench-check", "--history", p]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["status"] == "ok"
    assert "value" in out["checked"]
    assert out["breaches"] == []


def test_bench_check_breach_exit_one_and_names_metric(tmp_path, capsys):
    p = _history(tmp_path / "h.jsonl",
                 _rows(6, queries_per_sec=10_000.0,    # −80%: breach
                       query_p50_ms=100.0,             # 10× worse: breach
                       compile_s=21.0))                # within tolerance
    assert cli_main(["bench-check", "--history", p]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["status"] == "breach"
    breached = {b["metric"] for b in out["breaches"]}
    assert breached == {"queries_per_sec", "query_p50_ms"}


def test_bench_check_noise_floor_absorbs_tiny_absolute_swings(tmp_path,
                                                              capsys):
    # p50 0.4 ms -> 0.6 ms is +50% relative but under the 2 ms floor:
    # scheduler jitter, not a regression
    rows = _rows(6)
    for r in rows:
        r["query_p50_ms"] = 0.4
    rows[-1]["query_p50_ms"] = 0.6
    p = _history(tmp_path / "h.jsonl", rows)
    assert cli_main(["bench-check", "--history", p]) == 0
    assert json.loads(capsys.readouterr().out)["status"] == "ok"


def test_bench_check_envelope_absorbs_revisited_values(tmp_path, capsys):
    # the window itself swung 100..500 on identical code (this
    # container's measured weather): a new 150 is 50% below the median
    # but INSIDE the observed envelope — weather, not a regression
    rows = _rows(6)
    for r, qps in zip(rows, (100.0, 300.0, 500.0, 450.0, 120.0, 150.0)):
        r["queries_per_sec"] = qps
    p = _history(tmp_path / "h.jsonl", rows)
    assert cli_main(["bench-check", "--history", p]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["status"] == "ok"
    # but a value the trajectory has NEVER visited still breaches
    rows[-1]["queries_per_sec"] = 40.0
    p = _history(tmp_path / "h.jsonl", rows)
    assert cli_main(["bench-check", "--history", p]) == 1
    capsys.readouterr()


def test_bench_check_recompile_regression_breaches(tmp_path, capsys):
    p = _history(tmp_path / "h.jsonl", _rows(6, recompiles=12))
    assert cli_main(["bench-check", "--history", p]) == 1
    out = json.loads(capsys.readouterr().out)
    assert [b["metric"] for b in out["breaches"]] == ["recompiles"]


def test_bench_check_survives_torn_binary_append(tmp_path, capsys):
    # a writer killed mid-append can leave a partial multi-byte UTF-8
    # sequence; the gate must skip the torn line, not traceback
    p = tmp_path / "h.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in _rows(6)))
    with p.open("ab") as f:
        f.write(b'{"config": "ref", "va\xc3')   # torn mid-rune
    assert cli_main(["bench-check", "--history", str(p)]) == 0
    assert json.loads(capsys.readouterr().out)["status"] == "ok"


def test_bench_check_insufficient_history_exit_two(tmp_path, capsys):
    p = _history(tmp_path / "h.jsonl", _rows(2))
    assert cli_main(["bench-check", "--history", p]) == 2
    out = json.loads(capsys.readouterr().out)
    assert out["status"] == "insufficient_history"
    # --self-test maps the same state to a clean skip
    assert cli_main(["bench-check", "--history", p, "--self-test"]) == 0


def test_bench_check_groups_by_config_and_backend(tmp_path, capsys):
    # five tpu rows cannot vouch for a cpu row: comparable = same
    # (config, backend, build_only) key only
    rows = [dict(r, backend="tpu") for r in _rows(5)]
    rows.append(dict(_rows(1)[0], backend="cpu"))
    p = _history(tmp_path / "h.jsonl", rows)
    assert cli_main(["bench-check", "--history", p]) == 2


def test_bench_check_negative_sentinels_are_excluded(tmp_path, capsys):
    # -1.0 means "measurement failed", not "latency of -1 ms"
    p = _history(tmp_path / "h.jsonl", _rows(6, query_p50_ms=-1.0))
    assert cli_main(["bench-check", "--history", p]) == 0
    out = json.loads(capsys.readouterr().out)
    assert "query_p50_ms" not in out["checked"]


def test_bench_check_self_test_gates_the_checked_in_history():
    """The tier-1 gate: bench-check over the repo's own
    BENCH_HISTORY.jsonl must exit 0 — either a genuine pass once the
    history is deep enough, or the explicit clean skip while it is not
    (the lint-self-check pattern: the gate gates itself)."""
    assert cli_main(["bench-check", "--self-test"]) == 0


def test_bench_rows_carry_the_profiling_fields():
    import bench

    f = profiled_jit(lambda x: x * 7.0, label="bench_fn")
    f(np.zeros(4, np.float32))
    out = bench.profile_breakdown()
    assert set(bench.PROFILE_KEYS) <= set(out)
    assert out["compile_s"] > 0
    assert out["recompiles"] == 0
    assert out["peak_hbm_bytes"] == -1   # CPU backend: no memory_stats
