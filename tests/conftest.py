"""Test harness config: force an 8-device virtual CPU mesh before JAX loads.

Mirrors the reference's "local mode" testing stance (SURVEY.md §4): the same
SPMD code paths run on fake CPU devices, no TPU required.

The container may register an external TPU PJRT plugin ("axon") via
sitecustomize whose initialization contacts a tunnel; tests must be hermetic,
so after importing jax we drop that factory entirely — otherwise any
jax.devices() call would try (and possibly hang) to initialize it.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

# a pytest plugin may have imported jax before this conftest ran, freezing
# jax_platforms at the container's env value; override it in-config too
jax.config.update("jax_platforms", "cpu")

for _name in list(_xb._backend_factories):
    if _name != "cpu":
        _xb._backend_factories.pop(_name, None)

assert len(jax.devices("cpu")) == 8, "expected 8 virtual CPU devices"

import threading  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _lint_self_test():
    """The lint gate's own gate (ISSUE 14, mirroring bench-check
    --self-test): once per tier-1 session, every rule must still fire
    on its seeded positive fixture and stay silent on the negative.
    The per-rule self-check over the shipped package (test_lint.py)
    proves the CODE is clean; this proves the ANALYZERS still work —
    a pass that silently stops matching fails here, not never."""
    from tpu_ir.lint.selftest import run_selftest

    failures = run_selftest()
    assert not failures, "lint rule self-test failures:\n" + "\n".join(
        failures)


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Reset the process-wide telemetry (registry counters + histograms,
    trace ring, flight-recorder rate limiter) around every test.

    Before ISSUE 3 the counters were per-module singletons with no
    between-run reset, so `tpu-ir stats` / serve-bench assertions
    silently depended on which tests ran first — this fixture is the
    bleed-through fix: every test starts from zero and leaves zero."""
    from tpu_ir import obs

    obs.reset_all()
    yield
    obs.reset_all()


@pytest.fixture(autouse=True)
def _ordered_locks(request, monkeypatch):
    """TSan-lite lock-order verification for the serving/chaos tests
    (ISSUE 6): every threading.Lock/RLock CREATED by repo code during
    these tests is swapped for lint.OrderedLock, which records the
    acquisition order per thread and raises LockOrderInversion the
    moment two locks are ever taken in both orders — deterministically,
    on every schedule, instead of needing the one unlucky interleaving
    that deadlocks. The chaos soak therefore re-verifies the whole
    serving stack's lock discipline on every tier-1 run. Locks created
    by jax/stdlib internals keep their real classes (the factory checks
    the creation site's filename)."""
    if request.module.__name__.rsplit(".", 1)[-1] not in (
            "test_serving", "test_router", "test_cache_tier"):
        yield
        return
    from tpu_ir.lint import ordered_lock

    graph = ordered_lock.install(monkeypatch, strict=True)
    yield
    assert not graph.inversions, (
        "lock-order inversions recorded during test: "
        + "; ".join(graph.inversions))


@pytest.fixture(autouse=True)
def _no_leaked_nondaemon_threads():
    """Fail any test that leaks a live non-daemon thread.

    The serving/fault layers run work on threads by design (deadline
    dispatches, soak workers, admission waiters) — but every one of them
    must be daemon or joined by test end. A leaked non-daemon thread
    would outlive its test, block interpreter exit, and silently defeat
    the abandoned-dispatch cap this suite exists to enforce."""
    before = set(threading.enumerate())
    yield
    leaked = [t for t in threading.enumerate()
              if t not in before and t.is_alive() and not t.daemon]
    for t in leaked:          # grace: threads mid-shutdown may just need
        t.join(timeout=2.0)   # a moment to exit cleanly
    leaked = [t for t in leaked if t.is_alive()]
    assert not leaked, ("test leaked non-daemon thread(s): "
                        + ", ".join(repr(t) for t in leaked))
    # telemetry infrastructure threads (the embedded metrics HTTP server
    # and the spool writer, tpu_ir/obs/server.py + aggregate.py) are
    # DAEMONS by design — daemonhood is the crash backstop, not a
    # license to leak. They carry the "tpu-ir-obs" name prefix exactly
    # so this guard can hold tests to the orderly-stop contract
    # (MetricsServer.stop() / SpoolWriter.stop()).
    obs_leaked = [t for t in threading.enumerate()
                  if t not in before and t.is_alive()
                  and t.name.startswith("tpu-ir-obs")]
    for t in obs_leaked:
        t.join(timeout=2.0)
    obs_leaked = [t for t in obs_leaked if t.is_alive()]
    assert not obs_leaked, (
        "test left telemetry server/spool thread(s) running (call "
        ".stop()): " + ", ".join(repr(t) for t in obs_leaked))
