"""Test harness config: force an 8-device virtual CPU mesh before JAX loads.

Mirrors the reference's "local mode" testing stance (SURVEY.md §4): the same
SPMD code paths run on fake CPU devices, no TPU required.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
