"""Test harness config: force an 8-device virtual CPU mesh before JAX loads.

Mirrors the reference's "local mode" testing stance (SURVEY.md §4): the same
SPMD code paths run on fake CPU devices, no TPU required.

The container may register an external TPU PJRT plugin ("axon") via
sitecustomize whose initialization contacts a tunnel; tests must be hermetic,
so after importing jax we drop that factory entirely — otherwise any
jax.devices() call would try (and possibly hang) to initialize it.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

# a pytest plugin may have imported jax before this conftest ran, freezing
# jax_platforms at the container's env value; override it in-config too
jax.config.update("jax_platforms", "cpu")

for _name in list(_xb._backend_factories):
    if _name != "cpu":
        _xb._backend_factories.pop(_name, None)

assert len(jax.devices("cpu")) == 8, "expected 8 virtual CPU devices"
