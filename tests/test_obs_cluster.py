"""Acceptance suite for the cluster observability layer (ISSUE 4).

Pins the four contracts of the JobTracker top layer:

- **job/progress model** (obs/progress.py): phase counters, bounded
  history, and the monotone percent-complete contract `/jobs` pollers
  rely on;
- **aggregation math** (obs/aggregate.py): merging N process snapshots
  equals counter sums, histogram merge is associative/commutative, and
  the file spool dedupes cumulative generations per process;
- **snapshot seq/resets stamps** (registry): seq strictly monotonic,
  resets detectable by concurrent scrapers, and — the narrow-fix
  contract — read-and-zero racing scrapes lose no event and double
  none;
- **HTTP endpoints** (obs/server.py): a live server on an ephemeral
  port serves parseable Prometheus text, a /healthz with
  breaker/ladder/queue fields, /jobs progress that only moves forward
  mid-soak, and /flight incident headers — and stops cleanly (the
  conftest leak guard watches the tpu-ir-obs thread names).
"""

import json
import random
import re
import threading
import time
import urllib.error
import urllib.request

import pytest

from tpu_ir import obs
from tpu_ir.index.streaming import build_index_streaming
from tpu_ir.obs import aggregate
from tpu_ir.obs.histogram import NUM_BUCKETS, LatencyHistogram
from tpu_ir.obs.progress import start_job, report_progress, tracked
from tpu_ir.obs.registry import SNAPSHOT_SCHEMA, TelemetryRegistry
from tpu_ir.obs.server import MetricsServer
from tpu_ir.search import Scorer
from tpu_ir.serving import ServingConfig, ServingFrontend, run_soak

WORDS = ("granite basalt quartz mica shale slate marble gneiss "
         "delta river canyon mesa butte ridge summit valley".split())


@pytest.fixture(scope="module")
def index_dir(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("obs_cluster")
    body = []
    for i in range(100):
        text = " ".join(WORDS[(i + j) % len(WORDS)]
                        for j in range(3 + (i % 6)))
        body.append(f"<DOC>\n<DOCNO> R-{i:04d} </DOCNO>\n<TEXT>\n"
                    f"{text}\n</TEXT>\n</DOC>\n")
    corpus = tmp / "corpus.trec"
    corpus.write_text("".join(body))
    out = str(tmp / "idx")
    build_index_streaming([str(corpus)], out, k=1, num_shards=3,
                          batch_docs=40, chargram_ks=[])
    return out


@pytest.fixture(scope="module")
def scorer(index_dir):
    s = Scorer.load(index_dir, layout="sparse")
    s.search_batch(["granite river"], k=5, scoring="bm25")
    s.search_batch(["granite river"], k=5, scoring="tfidf")
    s.search_batch(["granite river"], k=5, rerank=25)
    return s


def _get(url: str, timeout: float = 10.0) -> tuple[int, bytes]:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _get_json(url: str):
    code, body = _get(url)
    assert code == 200, (code, body[:300])
    return json.loads(body)


# ---------------------------------------------------------------------------
# the job/progress model
# ---------------------------------------------------------------------------


def test_job_phases_counters_and_percent():
    job = start_job("build", "unit", phases=("map", "reduce"),
                    config={"k": 1})
    job.report("map", advance=3, total=10, docs_parsed=120)
    d = job.to_dict()
    assert d["state"] == "running" and d["current_phase"] == "map"
    assert d["phases"][0]["done"] == 3 and d["phases"][0]["total"] == 10
    assert d["phases"][0]["counters"]["docs_parsed"] == 120
    assert d["percent"] == pytest.approx(100 * 0.3 / 2, abs=0.01)
    # entering the later phase closes "map" for the percent computation
    job.report("reduce", total=4)
    assert job.to_dict()["percent"] >= 50.0
    job.report("reduce", advance=4)
    job.finish()
    d = job.to_dict()
    assert d["state"] == "succeeded" and d["percent"] == 100.0
    assert "eta_s" not in d


def test_job_percent_is_monotone_even_when_totals_move():
    job = start_job("build", "moving-total", phases=("p",))
    job.report("p", advance=8, total=10)
    p1 = job.to_dict()["percent"]
    # a resume revising the total UP must not walk the needle backwards
    job.report("p", total=100)
    assert job.to_dict()["percent"] >= p1


def test_job_eta_from_throughput():
    job = start_job("soak", "eta", phases=("serve",))
    job.report("serve", total=100)
    job._phases["serve"]["started"] = time.time() - 10.0  # 10s elapsed
    job.report("serve", advance=50)                       # -> 5/s
    eta = job.to_dict()["eta_s"]
    assert 7.0 < eta < 13.0  # ~10s remaining at the observed rate


def test_report_progress_targets_newest_running_job_or_noops():
    report_progress("anywhere", advance=1)      # no job: a silent no-op
    with tracked("build", "outer", phases=("a",)) as job:
        report_progress("a", advance=2)
        assert job.to_dict()["phases"][0]["done"] == 2
    # finished: report_progress no longer targets it
    report_progress("a", advance=5)
    assert job.to_dict()["phases"][0]["done"] == 2
    assert job.to_dict()["state"] == "succeeded"


def test_tracked_marks_failures_and_history_is_bounded():
    with pytest.raises(ValueError):
        with tracked("build", "doomed"):
            raise ValueError("boom")
    failed = [j for j in obs.progress.jobs() if j.name == "doomed"]
    assert failed and failed[0].state == "failed"
    assert "boom" in failed[0].to_dict()["error"]
    for i in range(40):          # history cap (default 16) holds
        start_job("build", f"spam-{i}").finish()
    assert len(obs.progress.jobs()) <= 16


# ---------------------------------------------------------------------------
# aggregation math (satellite: property tests)
# ---------------------------------------------------------------------------


def _random_registry(rng: random.Random) -> TelemetryRegistry:
    reg = TelemetryRegistry()
    for _ in range(rng.randint(5, 30)):
        reg.incr(rng.choice(["serving.submitted", "recovery.retries",
                             "fault.score.hang", "test.other"]),
                 rng.randint(1, 7))
    for _ in range(rng.randint(10, 60)):
        reg.observe(rng.choice(["dispatch", "request.full", "build.spill"]),
                    rng.lognormvariate(-6.0, 2.0))
    return reg


def test_merge_of_n_process_snapshots_equals_counter_sums():
    rng = random.Random(11)
    regs = [_random_registry(rng) for _ in range(5)]
    snaps = [r.collect_state() for r in regs]
    merged = aggregate.merge_snapshots(snaps)
    assert merged["processes"] == 5
    keys = {k for s in snaps for k in s["counters"]}
    for k in keys:
        assert merged["counters"][k] == sum(
            s["counters"].get(k, 0) for s in snaps), k
    # histogram totals: cluster count == sum of per-process counts, and
    # the merged summary equals one registry fed the union bucket-wise
    for name in {n for s in snaps for n in s["histograms"]}:
        want = sum(sum(s["histograms"][name]["counts"])
                   for s in snaps if name in s["histograms"])
        assert merged["histograms"][name]["count"] == want, name


def test_merge_is_permutation_invariant_and_histogram_merge_assoc():
    rng = random.Random(23)
    snaps = [_random_registry(rng).collect_state() for _ in range(4)]
    a = aggregate.merge_snapshots(snaps)
    b = aggregate.merge_snapshots(list(reversed(snaps)))
    assert a["counters"] == b["counters"]
    assert a["histograms"] == b["histograms"]
    # LatencyHistogram.merge: associative and commutative on raw buckets
    def fill(seed):
        h = LatencyHistogram()
        r = random.Random(seed)
        for _ in range(300):
            h.observe(r.expovariate(50.0))
        return h
    def merged(*hs):
        out = LatencyHistogram()
        for h in hs:
            out.merge(h)
        return out.state()
    ha, hb, hc = fill(1), fill(2), fill(3)
    ab = merged(ha, hb)
    ab_c = merged(ha, hb, hc)
    # commutative
    assert ab == merged(hb, ha)
    # associative: (a+b)+c == a+(b+c)
    left = LatencyHistogram()
    left.merge(ha); left.merge(hb); left.merge(hc)
    right_bc = LatencyHistogram()
    right_bc.merge(hb); right_bc.merge(hc)
    right = LatencyHistogram()
    right.merge(ha); right.merge(right_bc)
    assert left.state() == right.state() == ab_c


def test_merge_rejects_future_schema_and_foreign_buckets():
    good = TelemetryRegistry().collect_state()
    with pytest.raises(ValueError, match="newer"):
        aggregate.merge_snapshots([good, {**good, "schema": 99}])
    bad = json.loads(json.dumps(good))
    bad["histograms"]["dispatch"] = {"counts": [0] * (NUM_BUCKETS - 1),
                                     "sum_s": 0.0}
    with pytest.raises(ValueError, match="buckets"):
        aggregate.merge_snapshots([bad])


def test_spool_roundtrip_dedupes_generations(tmp_path, monkeypatch):
    d = str(tmp_path / "spool")
    monkeypatch.setenv("TPU_IR_TELEMETRY_DIR", d)
    reg = obs.get_registry()
    reg.incr("serving.submitted", 3)
    assert aggregate.spool_write() is not None
    reg.incr("serving.submitted", 4)     # newer cumulative generation
    assert aggregate.spool_write() is not None
    snaps = aggregate.read_spool(d)
    assert len(snaps) == 1               # one live file per run_id
    assert snaps[0]["counters"]["serving.submitted"] == 7
    # a second "process": a foreign run_id spooled by hand
    other = json.loads(json.dumps(snaps[0]))
    other["run_id"] = "deadbeef"
    other["pid"] = 999999
    (tmp_path / "spool" / "telemetry-otherhost-999999-000001.json"
     ).write_text(json.dumps(other))
    merged = aggregate.merge_snapshots(aggregate.read_spool(d))
    assert merged["processes"] == 2
    assert merged["counters"]["serving.submitted"] == 14


def test_merge_spool_counts_the_spooling_process_once(tmp_path,
                                                      monkeypatch):
    """A serving process that both spools and answers /cluster must not
    double-count itself: its live snapshot displaces its own spooled
    generation (same run_id), it does not add to it."""
    d = str(tmp_path / "spool")
    monkeypatch.setenv("TPU_IR_TELEMETRY_DIR", d)
    reg = obs.get_registry()
    reg.incr("serving.submitted", 8)
    assert aggregate.spool_write() is not None
    merged = aggregate.merge_spool(include_local=True)
    assert merged["processes"] == 1
    assert merged["counters"]["serving.submitted"] == 8
    # a foreign process in the spool still counts separately
    other = TelemetryRegistry()
    other.incr("serving.submitted", 5)
    s = other.collect_state()
    s["host"], s["pid"] = "h", 424242
    (tmp_path / "spool" / "telemetry-h-424242-000001.json").write_text(
        json.dumps(s))
    merged = aggregate.merge_spool(include_local=True)
    assert merged["processes"] == 2
    assert merged["counters"]["serving.submitted"] == 13


def test_cluster_cli_merges_the_spool(tmp_path, capsys):
    from tpu_ir.cli import main

    d = tmp_path / "spool"
    d.mkdir()
    for i, n in enumerate((5, 11)):
        snap = TelemetryRegistry()
        snap.incr("serving.submitted", n)
        snap.incr("recovery.retries", i)
        s = snap.collect_state()
        s["host"], s["pid"] = "h", 1000 + i
        (d / f"telemetry-h-{1000 + i}-000001.json").write_text(
            json.dumps(s))
    assert main(["metrics", "--cluster", "--telemetry-dir", str(d)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["processes"] == 2
    assert out["counters"]["serving.submitted"] == 16
    assert out["counters"]["recovery.retries"] == 1
    assert main(["stats", "--cluster", "--telemetry-dir", str(d)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["serving"]["submitted"] == 16
    assert out["processes"] == 2
    # no spool -> clean usage error, not a traceback
    assert main(["metrics", "--cluster", "--telemetry-dir",
                 str(tmp_path / "nope")]) == 1


# ---------------------------------------------------------------------------
# snapshot stamps: schema / seq / resets (+ the --reset narrow fix)
# ---------------------------------------------------------------------------


def test_snapshots_carry_monotonic_seq_and_reset_count():
    reg = obs.get_registry()
    s1 = reg.snapshot()
    s2 = reg.snapshot(reset=True)
    s3 = reg.snapshot()
    assert s1["schema"] == s2["schema"] == SNAPSHOT_SCHEMA
    assert s1["seq"] < s2["seq"] < s3["seq"]
    assert s2["resets"] == s1["resets"] + 1 == s3["resets"]
    assert s1["run_id"] == s3["run_id"]
    # a full reset() also announces itself; seq stays monotonic through
    reg.reset()
    s4 = reg.snapshot()
    assert s4["resets"] == s3["resets"] + 1
    assert s4["seq"] > s3["seq"]


def test_flight_header_carries_schema_and_seq(tmp_path):
    p1 = obs.flight_dump("unit_reason", out_dir=str(tmp_path), force=True)
    p2 = obs.flight_dump("unit_reason", out_dir=str(tmp_path), force=True)
    h1 = json.loads(open(p1).readline())
    h2 = json.loads(open(p2).readline())
    assert h1["record"] == "header" and h1["schema"] == 1
    assert h2["seq"] > h1["seq"]


def test_concurrent_reset_scrapes_lose_nothing_double_nothing():
    """The narrow fix pinned: producers increment while two drainers
    scrape with reset=True — every increment lands in exactly one
    drained interval (or the final sweep), and the seq/resets stamps
    order the intervals."""
    reg = obs.get_registry()
    N_PRODUCERS, PER = 4, 500
    drained = []
    stop = threading.Event()

    def produce():
        for _ in range(PER):
            reg.incr("serving.submitted")

    def drain():
        while not stop.is_set():
            drained.append(reg.snapshot(reset=True))

    producers = [threading.Thread(target=produce) for _ in range(N_PRODUCERS)]
    drainers = [threading.Thread(target=drain) for _ in range(2)]
    for t in drainers + producers:
        t.start()
    for t in producers:
        t.join()
    stop.set()
    for t in drainers:
        t.join()
    drained.append(reg.snapshot(reset=True))   # the final sweep
    total = sum(s["counters"].get("serving.submitted", 0) for s in drained)
    assert total == N_PRODUCERS * PER
    seqs = [s["seq"] for s in drained]
    assert len(set(seqs)) == len(seqs)         # every scrape distinct
    # within one thread's drain sequence, seq and resets only grow
    assert all(s["resets"] >= 1 for s in drained)


# ---------------------------------------------------------------------------
# HTTP endpoints (ephemeral port, urllib)
# ---------------------------------------------------------------------------

_PROM_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [0-9eE+.\-inf]+$')


def _assert_prometheus_parses(text: str) -> int:
    """Every non-comment line is `name{labels} value`; cumulative bucket
    counts are non-decreasing per stage and +Inf equals _count."""
    lines = [ln for ln in text.splitlines() if ln]
    assert lines, "empty exposition"
    n = 0
    cum: dict[str, list] = {}
    for ln in lines:
        if ln.startswith("#"):
            continue
        assert _PROM_LINE.match(ln), f"unparseable line: {ln!r}"
        n += 1
        m = re.match(r'.*\{stage="([^"]+)",le="([^"]+)"\} (\d+)$', ln)
        if m:
            cum.setdefault(m.group(1), []).append(int(m.group(3)))
    for stage, counts in cum.items():
        assert counts == sorted(counts), f"{stage} buckets not cumulative"
    return n


def test_server_endpoints_metrics_jobs_flight(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_IR_FLIGHT_DIR", str(tmp_path / "flight"))
    reg = obs.get_registry()
    reg.incr("serving.submitted", 9)
    reg.observe("dispatch", 0.004)
    job = start_job("build", "endpoint-unit", phases=("map", "reduce"))
    job.report("map", advance=2, total=4, docs_parsed=37)
    obs.flight_dump("unit_incident", force=True)
    srv = MetricsServer(port=0)
    srv.start()
    try:
        # /metrics: parseable Prometheus text; read-only (reset refused)
        code, body = _get(f"{srv.url}/metrics")
        assert code == 200
        text = body.decode()
        assert 'tpu_ir_events_total{name="serving.submitted"} 9' in text
        assert _assert_prometheus_parses(text) > 10
        code, _ = _get(f"{srv.url}/metrics?reset=1")
        assert code == 403
        assert reg.get("serving.submitted") == 9     # nothing drained
        # /metrics.json carries the stamps
        mj = _get_json(f"{srv.url}/metrics.json")
        assert mj["schema"] == SNAPSHOT_SCHEMA and mj["seq"] > 0
        # /jobs + /jobs/<id>, JSON and the JobTracker HTML echo
        jobs = _get_json(f"{srv.url}/jobs")["jobs"]
        mine = [j for j in jobs if j["name"] == "endpoint-unit"][0]
        assert mine["phases"][0]["counters"]["docs_parsed"] == 37
        one = _get_json(f"{srv.url}/jobs/{mine['job_id']}")
        assert one["percent"] == mine["percent"]
        code, html_body = _get(
            f"{srv.url}/jobs/{mine['job_id']}?format=html")
        assert code == 200
        page = html_body.decode()
        assert "<table>" in page and "endpoint-unit" in page
        assert "docs_parsed=37" in page
        code, _ = _get(f"{srv.url}/jobs/999999")
        assert code == 404
        # /flight: the incident header index
        fl = _get_json(f"{srv.url}/flight")["flight_records"]
        assert any(h["reason"] == "unit_incident" and "schema" in h
                   for h in fl)
        # /healthz exists even with no frontend registered
        hz = _get_json(f"{srv.url}/healthz")
        assert hz["status"] == "ok"
        assert "breaker" in hz and "ladder" in hz and "queue_depth" in hz
    finally:
        srv.stop()
    # after stop(): the port actually closed
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        urllib.request.urlopen(f"{srv.url}/healthz", timeout=2)


def test_healthz_reports_frontend_control_plane(scorer):
    frontend = ServingFrontend(scorer, ServingConfig(max_concurrency=2))
    frontend.search("granite river", k=5)
    with MetricsServer(port=0) as srv:
        hz = _get_json(f"{srv.url}/healthz")
        assert hz["breaker"]["state"] == "closed"
        assert hz["ladder"]["level"] == "full"
        assert hz["queue_depth"] == 0
        # [-1]: a frontend from an earlier test may still be alive (the
        # weakref registry keeps every live one); ours is the newest,
        # and it is the one the top-level breaker/ladder fields lift
        assert hz["frontends"][-1]["submitted"] == 1


def _poll_until(pred, timeout_s=30.0, interval_s=0.02):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        v = pred()
        if v:
            return v
        time.sleep(interval_s)
    raise AssertionError("condition not reached in time")


def _soak_with_server(scorer, queries, threads, fault_spec=None):
    """Drive run_soak on a worker thread with a live server; scrape
    /jobs, /metrics and /healthz mid-run; return (report, percents)."""
    report_box = {}
    with MetricsServer(port=0) as srv:
        t = threading.Thread(
            target=lambda: report_box.update(r=run_soak(
                scorer, threads=threads, queries=queries, seed=5,
                fault_spec=fault_spec,
                config=ServingConfig(max_concurrency=4, max_queue=16,
                                     deadline_s=5.0),
                timeout_s=120.0)),
            name="soak-driver")
        t.start()
        try:
            # the soak job appears and progresses while requests fly
            def soak_job():
                js = _get_json(f"{srv.url}/jobs")["jobs"]
                mine = [j for j in js if j["kind"] == "soak"]
                return mine[0] if mine else None

            job = _poll_until(soak_job)
            job_id = job["job_id"]
            percents = []
            while t.is_alive():
                d = _get_json(f"{srv.url}/jobs/{job_id}")
                percents.append(d["percent"])
                code, body = _get(f"{srv.url}/metrics")
                assert code == 200
                _assert_prometheus_parses(body.decode())
                hz = _get_json(f"{srv.url}/healthz")
                assert ("breaker" in hz and "ladder" in hz
                        and "queue_depth" in hz)
                time.sleep(0.02)
            percents.append(_get_json(f"{srv.url}/jobs/{job_id}")["percent"])
        finally:
            t.join(timeout=120.0)
    assert not t.is_alive()
    return report_box["r"], percents


def test_soak_failure_after_reference_marks_job_failed(scorer):
    """An escape AFTER the reference phase (here: a malformed fault
    spec) must still mark the soak job failed — never a ghost job stuck
    'running' in /jobs and /healthz's jobs_running."""
    with pytest.raises(ValueError):
        run_soak(scorer, threads=2, queries=4, seed=1,
                 fault_spec="seed=bogus")
    soaks = [j for j in obs.progress.jobs() if j.kind == "soak"]
    assert soaks and soaks[-1].state == "failed"
    assert "bogus" in soaks[-1].error


def test_mid_soak_scrapes_metrics_healthz_and_monotone_jobs(scorer):
    """THE acceptance criterion: during a soak with a live metrics
    server, mid-run scrapes return parseable /metrics Prometheus text,
    a /healthz with breaker/ladder/queue fields, and /jobs progress
    that only moves forward."""
    report, percents = _soak_with_server(scorer, queries=80, threads=4)
    assert report["errors"] == 0 and report["deadlocked"] == 0
    assert len(percents) >= 3, "soak finished before any mid-run scrape"
    assert all(b >= a for a, b in zip(percents, percents[1:])), percents
    assert percents[-1] == 100.0


@pytest.mark.slow
def test_long_chaos_soak_with_server_slow(scorer):
    """The long variant: a chaos soak under the live server — progress
    stays monotone and the scrapes stay parseable while hangs and
    device losses fire."""
    from tpu_ir.serving.soak import DEFAULT_CHAOS_PLAN

    report, percents = _soak_with_server(
        scorer, queries=600, threads=8, fault_spec=DEFAULT_CHAOS_PLAN)
    assert report["errors"] == 0
    assert all(b >= a for a, b in zip(percents, percents[1:]))


# ---------------------------------------------------------------------------
# build jobs: the builders actually feed the tracker
# ---------------------------------------------------------------------------


def test_streaming_build_registers_a_tracked_job(tmp_path):
    body = "".join(
        f"<DOC>\n<DOCNO> J-{i:03d} </DOCNO>\n<TEXT>\nalpha beta g{i}\n"
        f"</TEXT>\n</DOC>\n" for i in range(30))
    corpus = tmp_path / "c.trec"
    corpus.write_text(body)
    # the LEGACY per-batch phase shape is what this test pins
    # (one spill per batch, pass2 done == batches); the radix default
    # (ISSUE 13) tracks per-bucket progress, covered in test_radix.py
    build_index_streaming([str(corpus)], str(tmp_path / "idx"), k=1,
                          num_shards=2, batch_docs=10, chargram_ks=[],
                          radix_buckets=0)
    job = [j for j in obs.progress.jobs() if j.kind == "build"][-1]
    d = job.to_dict()
    assert d["state"] == "succeeded" and d["percent"] == 100.0
    by_phase = {p["phase"]: p for p in d["phases"]}
    assert by_phase["pass1_tokenize"]["counters"]["docs_parsed"] == 30
    # batch count tracks the tokenizer's chunking (one delta per corpus
    # chunk), so pin consistency, not a count: every pass-1 spill batch
    # became exactly one completed pass-2 step
    n_batches = by_phase["pass1_tokenize"]["done"]
    assert n_batches >= 1
    assert by_phase["pass1_tokenize"]["counters"]["spills_written"] == \
        n_batches
    assert by_phase["pass2_combine"]["done"] == n_batches
    assert by_phase["pass2_combine"]["total"] == n_batches
    assert by_phase["pass3_reduce"]["done"] == 2
    assert by_phase["pass3_reduce"]["counters"]["shards_reduced"] == 2


def test_failed_build_marks_its_job_failed(tmp_path):
    empty = tmp_path / "empty.trec"
    empty.write_text("no trec records here\n")
    with pytest.raises(ValueError):
        build_index_streaming([str(empty)], str(tmp_path / "idx2"),
                              k=1, num_shards=2)
    job = [j for j in obs.progress.jobs() if j.kind == "build"][-1]
    assert job.state == "failed"


def test_index_cli_track_serves_and_stops(tmp_path, capsys):
    """--track PORT: the build runs under a live server (URL announced
    on stderr) and the server is gone when the command returns (the
    conftest tpu-ir-obs leak guard enforces the 'gone')."""
    from tpu_ir.cli import main

    body = "".join(
        f"<DOC>\n<DOCNO> T-{i:03d} </DOCNO>\n<TEXT>\ngamma delta t{i}\n"
        f"</TEXT>\n</DOC>\n" for i in range(12))
    corpus = tmp_path / "c.trec"
    corpus.write_text(body)
    rc = main(["index", str(corpus), str(tmp_path / "idx"),
               "--no-chargrams", "--shards", "2", "--track", "0"])
    assert rc == 0
    err = capsys.readouterr().err
    assert "serving live telemetry on http://127.0.0.1:" in err
