import gzip
import io

import pytest

from tpu_ir.collection import DocnoMapping, Vocab, kgram_terms, read_trec_stream
from tpu_ir.collection.trec import read_trec_file


def make_corpus(docs: dict[str, str]) -> bytes:
    return b"".join(
        f"<DOC>\n<DOCNO> {docid} </DOCNO>\n<TEXT>\n{text}\n</TEXT>\n</DOC>\n".encode()
        for docid, text in docs.items()
    )


def test_stream_reader_basic():
    raw = make_corpus({"D1": "alpha beta", "D2": "gamma"})
    docs = list(read_trec_stream(io.BufferedReader(io.BytesIO(raw))))
    assert [d.docid for d in docs] == ["D1", "D2"]
    assert "alpha beta" in docs[0].content
    assert docs[0].offset == 0
    assert docs[1].offset == raw.find(b"<DOC>", 1)


def test_stream_reader_tiny_chunks_and_noise():
    # record split across chunk boundaries + garbage between records
    raw = b"junk " + make_corpus({"A": "x" * 50}) + b" mid-noise " + make_corpus({"B": "y"})
    docs = list(read_trec_stream(io.BufferedReader(io.BytesIO(raw)), chunk_size=7))
    assert [d.docid for d in docs] == ["A", "B"]


def test_gzip_transparent(tmp_path):
    raw = make_corpus({"G1": "zipped content"})
    p = tmp_path / "corpus.gz"
    p.write_bytes(gzip.compress(raw))
    docs = list(read_trec_file(p))
    assert [d.docid for d in docs] == ["G1"]


def test_docno_mapping_roundtrip(tmp_path):
    m = DocnoMapping.build(["WSJ-2", "AP-1", "FT-3", "AP-1"])
    # 1-based, sorted-docid order (reference NumberTrecDocuments semantics)
    assert len(m) == 3
    assert m.get_docno("AP-1") == 1
    assert m.get_docno("FT-3") == 2
    assert m.get_docno("WSJ-2") == 3
    assert m.get_docid(2) == "FT-3"
    with pytest.raises(KeyError):
        m.get_docno("NOPE")
    p = tmp_path / "docnos.txt"
    m.save(p)
    m2 = DocnoMapping.load(p)
    assert m2.docids == m.docids


def test_vocab_roundtrip(tmp_path):
    v = Vocab.build(["zebra", "apple", "mango", "apple"])
    assert len(v) == 3
    assert v.id("apple") == 0 and v.id("zebra") == 2
    assert v.term(1) == "mango"
    assert v.id_or("nope") == -1
    p = tmp_path / "vocab.txt"
    v.save(p)
    assert Vocab.load(p).terms == v.terms


def test_kgram_terms():
    toks = ["a", "b", "c", "d"]
    assert kgram_terms(toks, 1) == toks
    assert kgram_terms(toks, 2) == ["a b", "b c", "c d"]
    assert kgram_terms(toks, 4) == ["a b c d"]
    # shorter than k -> nothing (reference TermKGramDocIndexer.java:144-146)
    assert kgram_terms(["a"], 2) == []


# -- stream parsers + parsed Document model (collection/parsers.py) --------

TRECTEXT = """\
junk preamble
<DOC>
<DOCNO> AP-900101-0001 </DOCNO>
<FILEID>AP-NR-01-01-90</FILEID>
<HEAD>
Fish Stocks Rebound
</HEAD>
<IGNORED>not indexed</IGNORED>
<TEXT>
Salmon runs returned to the river.
Second line.
</TEXT>
</DOC>
<DOC>
<DOCNO>
 AP-2 </DOCNO>
<TEXT>
short
</TEXT>
</DOC>
"""


def test_trectext_parser_sections_and_multiline_docno():
    from tpu_ir.collection import TrecTextParser

    docs = list(TrecTextParser(TRECTEXT))
    assert [d.identifier for d in docs] == ["AP-900101-0001", "AP-2"]
    # only the known section tags' content is kept, tag lines included;
    # FILEID/IGNORED lines are dropped (TrecTextParser.java:58-63)
    assert "Fish Stocks Rebound" in docs[0].text
    assert "Salmon runs" in docs[0].text and "Second line." in docs[0].text
    # dropped: FILEID is no known section, IGNORED sits between sections.
    # (The reference's parser is line-oriented and a one-line <HEAD>x</HEAD>
    # would never close — TrecTextParser.java:66-89 — leaking every later
    # unknown-tag line into the text; this parser closes it, see
    # test_trectext_one_line_section_closes.)
    assert "FILEID" not in docs[0].text and "not indexed" not in docs[0].text
    assert docs[1].text == "<TEXT>\nshort\n</TEXT>\n"


def test_trectext_one_line_section_closes():
    """<TEXT>x</TEXT> on a single line must end the section there —
    leaving it open would index every following unknown-tag line up to
    </DOC> (review r5; the reference's line-oriented parser has this
    leak, TrecTextParser.java:66-89)."""
    from tpu_ir.collection import TrecTextParser

    raw = ("<DOC>\n<DOCNO> D-1 </DOCNO>\n"
           "<TEXT>hello world</TEXT>\n"
           "<JUNK>should be dropped</JUNK>\n</DOC>\n")
    docs = list(TrecTextParser(raw))
    assert len(docs) == 1
    assert "hello world" in docs[0].text
    assert "should be dropped" not in docs[0].text
    # multi-line sections still span lines and keep their end tag
    raw2 = ("<DOC>\n<DOCNO> D-2 </DOCNO>\n"
            "<TEXT>\nline one\n</TEXT>\n<SKIPPED>x</SKIPPED>\n</DOC>\n")
    d2 = list(TrecTextParser(raw2))[0]
    assert "line one" in d2.text and "</TEXT>" in d2.text
    assert "SKIPPED" not in d2.text


def test_docno_mapping_rejects_embedded_newline():
    """docnos.txt is one docid per line; an embedded newline (multi-line
    <DOCNO> keeps interior whitespace after strip) would shear the file
    and misalign every later docno on reload (review r5)."""
    from tpu_ir.collection import DocnoMapping

    with pytest.raises(ValueError, match="newline"):
        DocnoMapping.build(["AB\nCD", "EF"])


TRECWEB = """\
<DOC>
<DOCNO> WT01-B01-1 </DOCNO>
<DOCHDR>
HTTP://Example.COM:80/Path/# 199.0.0.1 19970101
Content-type: text/html
</DOCHDR>
<html><head><title>Example Page</title></head>
<body>web content here</body></html>
</DOC>
"""


def test_trecweb_parser_url_scrub_and_metadata():
    from tpu_ir.collection import TrecWebParser

    docs = list(TrecWebParser(TRECWEB))
    assert len(docs) == 1
    d = docs[0]
    assert d.identifier == "WT01-B01-1"
    # scrubbed: lowercase, no :80, no trailing '#', no trailing slashes
    # (TrecWebParser.java:37-53)
    assert d.metadata["url"] == "http://example.com/path"
    assert d.metadata["identifier"] == d.identifier
    assert "web content here" in d.text
    assert "Content-type" not in d.text  # header stays out of the content


def test_parse_document_terms_and_tags():
    from tpu_ir.collection import Document, parse_document

    doc = parse_document(Document(
        "X-1", '<title>Big News</title> hello <b>bold words</b>'))
    assert doc.terms == ["big", "news", "hello", "bold", "words"]
    assert [(t.name, t.begin, t.end) for t in doc.tags] == \
        [("title", 0, 2), ("b", 3, 5)]


def test_pack_roundtrip_into_index(tmp_path):
    """trecweb corpus -> pack --format trecweb -> canonical TREC that the
    native ingestion path indexes and retrieves."""
    from tpu_ir.collection import TrecWebParser, read_trec_file, to_trec

    out = tmp_path / "packed.trec"
    with open(out, "w") as f:
        for doc in TrecWebParser(TRECWEB):
            f.write(to_trec(doc))
    got = list(read_trec_file(str(out)))
    assert [d.docid for d in got] == ["WT01-B01-1"]
    assert "web content here" in got[0].content


def test_tag_spans_recorded():
    """Opt-in tag-span recording: token coordinates, (begin asc, end desc)
    order, nesting, attributes, self-closing tags, 256-byte name cap
    (Tag.java:8-77, TagTokenizer.java:626-642)."""
    from tpu_ir.analysis.tag_tokenizer import TagTokenizer

    t = TagTokenizer(record_tags=True)
    toks = t.tokenize('<doc id="7"><title>Big News</title> hello '
                      '<b>bold words</b> tail <br/> end</doc>')
    assert toks == ["big", "news", "hello", "bold", "words", "tail", "end"]
    spans = [(g.name, g.begin, g.end) for g in t.tags]
    # doc encloses everything; title/b are inner spans; br is empty
    assert spans == [("doc", 0, 7), ("title", 0, 2), ("b", 3, 5),
                     ("br", 6, 6)]
    assert t.tags[0].attributes == {"id": "7"}
    assert str(t.tags[0]) == '<doc id="7">'

    # default tokenizer records nothing (no cost on the indexing hot path)
    t2 = TagTokenizer()
    t2.tokenize("<a>x</a>")
    assert t2.tags == []

    # unmatched end tags are dropped; name capped below 256 UTF-8 bytes
    t3 = TagTokenizer(record_tags=True)
    t3.tokenize("</nope>w<" + "x" * 300 + ">y</" + "x" * 300 + ">")
    assert [g.name[:2] for g in t3.tags] == ["xx"]
    assert len(t3.tags[0].name.encode("utf-8")) < 256


def test_stream_parsers_malformed_input():
    """Truncated/malformed streams must end cleanly (None / partial), like
    the reference's readLine-until-EOF loops, never raise."""
    from tpu_ir.collection import TrecTextParser, TrecWebParser

    # empty and garbage streams -> no documents
    assert list(TrecTextParser("")) == []
    assert list(TrecWebParser("no trec here\njust text\n")) == []
    # truncated mid-record: TrecText yields the partial doc (reference
    # breaks out of the section loop at EOF and returns the buffer)
    docs = list(TrecTextParser(
        "<DOC>\n<DOCNO> X-1 </DOCNO>\n<TEXT>\ncut off"))
    assert [d.identifier for d in docs] == ["X-1"]
    assert "cut off" in docs[0].text
    # web record missing its DOCHDR -> stream ends with no document
    assert list(TrecWebParser("<DOC>\n<DOCNO> X-2 </DOCNO>\nbody\n</DOC>\n")) == []
    # DOCNO line split across lines (never closed) -> identifier is the
    # accumulated text up to EOF, no crash
    docs = list(TrecTextParser("<DOC>\n<DOCNO>\nX-3\n"))
    assert len(docs) == 1 and "X-3" in docs[0].identifier
    # bare '#' URL must not crash scrub_url (the reference's charAt(-1)
    # style would); empty URL line is tolerated
    assert TrecWebParser.scrub_url("#") == ""
    docs = list(TrecWebParser(
        "<DOC>\n<DOCNO> X-4 </DOCNO>\n<DOCHDR>\n\n</DOCHDR>\nb\n</DOC>\n"))
    assert docs[0].metadata["url"] == ""


def test_scrub_url_strips_all_port80_occurrences():
    """TrecWebParser.java:44-48 parity: ':80/' always collapses to '/';
    when the URL *ends* with ':80' the reference replaces ALL remaining
    ':80' occurrences, not just the trailing one."""
    from tpu_ir.collection import TrecWebParser

    s = TrecWebParser.scrub_url
    assert s("HTTP://Host:80/Path/") == "http://host/path"
    assert s("http://host:80") == "http://host"
    # ':80' mid-string not followed by '/', plus trailing ':80' ->
    # the endswith branch removes BOTH
    assert s("http://a:80b/c:80") == "http://ab/c"
    # no trailing ':80' -> the mid-string ':80' (not before '/') survives
    assert s("http://a:80b/c") == "http://a:80b/c"
