import gzip
import io

import pytest

from tpu_ir.collection import DocnoMapping, Vocab, kgram_terms, read_trec_stream
from tpu_ir.collection.trec import read_trec_file


def make_corpus(docs: dict[str, str]) -> bytes:
    return b"".join(
        f"<DOC>\n<DOCNO> {docid} </DOCNO>\n<TEXT>\n{text}\n</TEXT>\n</DOC>\n".encode()
        for docid, text in docs.items()
    )


def test_stream_reader_basic():
    raw = make_corpus({"D1": "alpha beta", "D2": "gamma"})
    docs = list(read_trec_stream(io.BufferedReader(io.BytesIO(raw))))
    assert [d.docid for d in docs] == ["D1", "D2"]
    assert "alpha beta" in docs[0].content
    assert docs[0].offset == 0
    assert docs[1].offset == raw.find(b"<DOC>", 1)


def test_stream_reader_tiny_chunks_and_noise():
    # record split across chunk boundaries + garbage between records
    raw = b"junk " + make_corpus({"A": "x" * 50}) + b" mid-noise " + make_corpus({"B": "y"})
    docs = list(read_trec_stream(io.BufferedReader(io.BytesIO(raw)), chunk_size=7))
    assert [d.docid for d in docs] == ["A", "B"]


def test_gzip_transparent(tmp_path):
    raw = make_corpus({"G1": "zipped content"})
    p = tmp_path / "corpus.gz"
    p.write_bytes(gzip.compress(raw))
    docs = list(read_trec_file(p))
    assert [d.docid for d in docs] == ["G1"]


def test_docno_mapping_roundtrip(tmp_path):
    m = DocnoMapping.build(["WSJ-2", "AP-1", "FT-3", "AP-1"])
    # 1-based, sorted-docid order (reference NumberTrecDocuments semantics)
    assert len(m) == 3
    assert m.get_docno("AP-1") == 1
    assert m.get_docno("FT-3") == 2
    assert m.get_docno("WSJ-2") == 3
    assert m.get_docid(2) == "FT-3"
    with pytest.raises(KeyError):
        m.get_docno("NOPE")
    p = tmp_path / "docnos.txt"
    m.save(p)
    m2 = DocnoMapping.load(p)
    assert m2.docids == m.docids


def test_vocab_roundtrip(tmp_path):
    v = Vocab.build(["zebra", "apple", "mango", "apple"])
    assert len(v) == 3
    assert v.id("apple") == 0 and v.id("zebra") == 2
    assert v.term(1) == "mango"
    assert v.id_or("nope") == -1
    p = tmp_path / "vocab.txt"
    v.save(p)
    assert Vocab.load(p).terms == v.terms


def test_kgram_terms():
    toks = ["a", "b", "c", "d"]
    assert kgram_terms(toks, 1) == toks
    assert kgram_terms(toks, 2) == ["a b", "b c", "c d"]
    assert kgram_terms(toks, 4) == ["a b c d"]
    # shorter than k -> nothing (reference TermKGramDocIndexer.java:144-146)
    assert kgram_terms(["a"], 2) == []
