"""Index merge: N indexes -> the union index, byte-identical to one build
over the concatenated corpus (the determinism contract of the format:
docnos = sorted-docid ranks, term ids = sorted-vocab ranks, postings in
(term asc, tf desc, doc asc))."""

import filecmp
import os

import numpy as np
import pytest

from tpu_ir.index import build_index
from tpu_ir.index import format as fmt
from tpu_ir.index.merge import merge_indexes
from tpu_ir.index.verify import verify_index
from tpu_ir.search import Scorer

DOCS_A = {
    "AP-0001": "The quick brown fox jumps over the lazy dog.",
    "AP-0002": "A quick quick quick fox. The dog sleeps soundly.",
    "ZF-077": "Honey prices rose as bears raided apiaries near the river.",
}
DOCS_B = {
    "FT-0003": "Stock markets fell sharply as investors fled risky assets.",
    "WSJ-9.2": "Salmon fishing season opened; fishermen crowded the rivers.",
    "AP-0010": "Brown bears eat honey. Bears love rivers and salmon fishing.",
}


def write_corpus(path, docs):
    path.write_text("".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in docs.items()))
    return str(path)


def artifact_names(index_dir):
    return sorted(
        n for n in os.listdir(index_dir)
        if not n.startswith(".") and n != fmt.JOBS_DIR
        and not n.startswith("serving-"))


@pytest.mark.parametrize("k,chargrams", [(1, [2, 3]), (2, [2])])
def test_merge_equals_direct_build(tmp_path, k, chargrams):
    ca = write_corpus(tmp_path / "a.trec", DOCS_A)
    cb = write_corpus(tmp_path / "b.trec", DOCS_B)
    cboth = write_corpus(tmp_path / "both.trec", {**DOCS_A, **DOCS_B})

    ia, ib = str(tmp_path / "ia"), str(tmp_path / "ib")
    build_index([ca], ia, k=k, chargram_ks=chargrams, num_shards=3)
    build_index([cb], ib, k=k, chargram_ks=chargrams, num_shards=3)
    direct = str(tmp_path / "direct")
    build_index([cboth], direct, k=k, chargram_ks=chargrams, num_shards=4)

    merged = str(tmp_path / "merged")
    meta = merge_indexes([ia, ib], merged, num_shards=4)
    assert meta.num_docs == len(DOCS_A) + len(DOCS_B)
    assert verify_index(merged)["ok"]

    # every artifact byte-identical to the one-shot build
    names = artifact_names(direct)
    assert artifact_names(merged) == names
    for n in names:
        assert filecmp.cmp(os.path.join(direct, n),
                           os.path.join(merged, n), shallow=False), n

    # and searching the merged index equals searching the direct one
    s1, s2 = Scorer.load(direct), Scorer.load(merged)
    for q in ["quick fox", "salmon fishing", "honey bears river"]:
        assert s1.search(q) == s2.search(q), q


def test_merge_rejects_bad_inputs(tmp_path):
    ca = write_corpus(tmp_path / "a.trec", DOCS_A)
    ia = str(tmp_path / "ia")
    build_index([ca], ia, k=1, num_shards=2, compute_chargrams=False)

    # overlapping docids
    with pytest.raises(ValueError, match="share docids"):
        merge_indexes([ia, ia], str(tmp_path / "dup"))

    # k mismatch
    ib = str(tmp_path / "ib2")
    cb = write_corpus(tmp_path / "b.trec", DOCS_B)
    build_index([cb], ib, k=2, num_shards=2, compute_chargrams=False)
    with pytest.raises(ValueError, match="different k"):
        merge_indexes([ia, ib], str(tmp_path / "mixk"))


def test_merge_single_source_resharding(tmp_path):
    """Merging one index is a reshard: same corpus, new shard count,
    same retrieval results."""
    ca = write_corpus(tmp_path / "a.trec", DOCS_A)
    ia = str(tmp_path / "ia")
    build_index([ca], ia, k=1, num_shards=5, compute_chargrams=False)
    out = str(tmp_path / "resharded")
    meta = merge_indexes([ia], out, num_shards=2,
                         compute_chargrams=False)
    assert meta.num_shards == 2
    assert verify_index(out)["ok"]
    s1, s2 = Scorer.load(ia), Scorer.load(out)
    assert s1.search("quick fox") == s2.search("quick fox")


def test_merge_guards(tmp_path):
    """Stale-output, source-as-output and missing-tokens.txt guards."""
    ca = write_corpus(tmp_path / "a.trec", DOCS_A)
    cb = write_corpus(tmp_path / "b.trec", DOCS_B)
    ia, ib = str(tmp_path / "ia"), str(tmp_path / "ib")
    build_index([ca], ia, k=1, num_shards=2, compute_chargrams=False)
    build_index([cb], ib, k=1, num_shards=2, compute_chargrams=False)

    out = str(tmp_path / "out")
    m1 = merge_indexes([ia], out, num_shards=2, compute_chargrams=False)
    # stale early-return without overwrite; real re-merge with it
    assert merge_indexes([ia, ib], out, num_shards=2,
                         compute_chargrams=False).num_docs == m1.num_docs
    m2 = merge_indexes([ia, ib], out, num_shards=2,
                       compute_chargrams=False, overwrite=True)
    assert m2.num_docs == len(DOCS_A) + len(DOCS_B)

    with pytest.raises(ValueError, match="must not be one of the sources"):
        merge_indexes([ia, out], out)

    # k>1 chargram merge requires every source's tokens.txt sidecar
    ja, jb = str(tmp_path / "ja"), str(tmp_path / "jb")
    build_index([ca], ja, k=2, chargram_ks=[2], num_shards=2)
    build_index([cb], jb, k=2, num_shards=2, compute_chargrams=False)
    with pytest.raises(ValueError, match="tokens.txt"):
        merge_indexes([ja, jb], str(tmp_path / "jm"))
    # explicit no-chargrams merge of the same pair is fine
    assert merge_indexes([ja, jb], str(tmp_path / "jm2"),
                         compute_chargrams=False).chargram_ks == []


def test_merge_mixed_builders(tmp_path):
    """A streaming-built and an in-memory-built index merge to the same
    bytes as one in-memory build over the concatenated corpus (the two
    builders share one artifact format — SURVEY §3's invariant)."""
    from tpu_ir.index.streaming import build_index_streaming

    ca = write_corpus(tmp_path / "a.trec", DOCS_A)
    cb = write_corpus(tmp_path / "b.trec", DOCS_B)
    cboth = write_corpus(tmp_path / "both.trec", {**DOCS_A, **DOCS_B})
    ia, ib = str(tmp_path / "ia"), str(tmp_path / "ib")
    build_index_streaming([ca], ia, k=1, chargram_ks=[2], num_shards=3,
                          batch_docs=2)
    build_index([cb], ib, k=1, chargram_ks=[2], num_shards=2)
    direct = str(tmp_path / "direct")
    build_index([cboth], direct, k=1, chargram_ks=[2], num_shards=3)
    merged = str(tmp_path / "merged")
    merge_indexes([ia, ib], merged, num_shards=3)
    for n in artifact_names(direct):
        assert filecmp.cmp(os.path.join(direct, n),
                           os.path.join(merged, n), shallow=False), n


def test_merge_carries_docstore_byte_identically(tmp_path):
    """Sources with document stores merge into a store byte-identical to
    a one-shot --store build over the concatenated corpus (same arrival
    order, same 256-doc zlib block cuts); a mixed merge (one source
    stored, one not) is an error, not a silent snippet-incapable output."""
    from tpu_ir.index import docstore as ds

    ca = write_corpus(tmp_path / "a.trec", DOCS_A)
    cb = write_corpus(tmp_path / "b.trec", DOCS_B)
    cboth = write_corpus(tmp_path / "both.trec", {**DOCS_A, **DOCS_B})

    ia, ib = str(tmp_path / "ia"), str(tmp_path / "ib")
    build_index([ca], ia, k=1, chargram_ks=[], num_shards=3)
    build_index([cb], ib, k=1, chargram_ks=[], num_shards=3)
    ds.build_docstore([ca], ia)
    ds.build_docstore([cb], ib)
    direct = str(tmp_path / "direct")
    build_index([cboth], direct, k=1, chargram_ks=[], num_shards=4)
    ds.build_docstore([cboth], direct)

    merged = str(tmp_path / "merged")
    merge_indexes([ia, ib], merged, num_shards=4)
    for name in ["docstore.bin", "docstore-idx.npz"]:
        assert filecmp.cmp(os.path.join(merged, name),
                           os.path.join(direct, name), shallow=False), name
    # and the merged store serves the right text by merged docno
    store = ds.DocStore(merged)
    docids = {**DOCS_A, **DOCS_B}
    from tpu_ir.collection import DocnoMapping

    mapping = DocnoMapping.load(os.path.join(merged, fmt.DOCNOS))
    for docid, text in docids.items():
        assert text in store.get(mapping.get_docno(docid)), docid
    store.close()

    # corrupt: a crash between bin and idx writes (truncated bin) must
    # refuse, not silently downgrade to a storeless merge
    with open(os.path.join(ib, "docstore.bin"), "ab") as f:
        f.write(b"x")
    with pytest.raises(ValueError, match="inconsistent"):
        merge_indexes([ia, ib], str(tmp_path / "mc"), num_shards=4)
    assert not os.path.exists(str(tmp_path / "mc"))  # failed before writes

    # mixed: ib loses its store -> merge must refuse
    os.unlink(os.path.join(ib, "docstore.bin"))
    os.unlink(os.path.join(ib, "docstore-idx.npz"))
    with pytest.raises(ValueError, match="document store"):
        merge_indexes([ia, ib], str(tmp_path / "m2"), num_shards=4)
    assert not os.path.exists(str(tmp_path / "m2"))
    # both storeless: merges fine, no store in the output
    os.unlink(os.path.join(ia, "docstore.bin"))
    os.unlink(os.path.join(ia, "docstore-idx.npz"))
    m3 = str(tmp_path / "m3")
    merge_indexes([ia, ib], m3, num_shards=4)
    assert not ds.available(m3)
