"""Live index subsystem (ISSUE 12): segments, tombstones, generations.

THE contract under test: a fully compacted generation is BIT-IDENTICAL
(metadata checksums equal — every artifact byte pinned) to a
from-scratch build over the surviving documents, across add/update/
delete sequences, flush boundaries, and merge orders. Plus the
manifest-chain mechanics (atomic commits, gc, live view), the tiered
merge policy, and the live doctor/verify surfaces.
"""

import json
import os
import random

import pytest

from tpu_ir.index import build_index
from tpu_ir.index import format as fmt
from tpu_ir.index.ingest import IngestWriter
from tpu_ir.index.segments import (
    LiveIndex,
    compact,
    drop_docs,
    is_live,
    latest_servable,
    merge_debt,
    plan_merges,
    resolve_serving,
)

WORDS = ("salmon fishing river bears honey quick brown fox lazy dog "
         "market investor asset bond stock season rain forest".split())

N_SHARDS = 3


def make_text(rng) -> str:
    return " ".join(rng.choice(WORDS) for _ in range(rng.randint(3, 8)))


def write_trec(path, docs: dict) -> str:
    with open(path, "w", encoding="utf-8") as f:
        for d, t in docs.items():
            f.write(f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n"
                    f"</TEXT>\n</DOC>\n")
    return str(path)


def scratch_build(tmp_path, docs: dict, name: str = "ref"):
    """From-scratch oracle: one build over `docs` with the live
    config's parameters (the checksum-equality comparand)."""
    corpus = write_trec(tmp_path / f"{name}.trec", docs)
    out = str(tmp_path / name)
    return build_index([corpus], out, num_shards=N_SHARDS)


def assert_bit_identical(meta_a, meta_b):
    """metadata checksums equal = every covered artifact byte-equal
    (parts, doclen, dictionary, docnos, vocab, chargrams)."""
    assert meta_a.num_docs == meta_b.num_docs
    assert meta_a.num_pairs == meta_b.num_pairs
    assert meta_a.vocab_size == meta_b.vocab_size
    assert meta_a.checksums, "oracle build recorded no checksums"
    assert meta_a.checksums == meta_b.checksums


# ---------------------------------------------------------------------------
# manifest-chain mechanics
# ---------------------------------------------------------------------------


def test_create_open_commit_roundtrip(tmp_path):
    live_dir = str(tmp_path / "live")
    live = LiveIndex.create(live_dir, num_shards=N_SHARDS)
    assert is_live(live_dir)
    assert live.current_gen() == 0
    assert live.manifest()["segments"] == []
    with pytest.raises(ValueError):
        LiveIndex.create(live_dir)  # already live
    with pytest.raises(ValueError):
        LiveIndex.create(str(tmp_path / "k2"), k=2)  # k=1 only
    with pytest.raises(ValueError):
        LiveIndex.open(str(tmp_path / "nowhere"))
    # an empty generation is not servable
    with pytest.raises(ValueError):
        resolve_serving(live_dir)
    # a plain dir resolves to itself at generation 0
    plain = tmp_path / "plain"
    plain.mkdir()
    assert resolve_serving(str(plain)) == (str(plain), 0)


def test_ingest_flush_tombstones_and_live_view(tmp_path):
    live_dir = str(tmp_path / "live")
    LiveIndex.create(live_dir, num_shards=N_SHARDS)
    rng = random.Random(0)
    w = IngestWriter(live_dir, buffer_docs=4, auto_merge=False)
    for i in range(10):  # buffer_docs=4 -> auto-flushes mint segments
        w.add(f"D-{i:03d}", make_text(rng))
    w.flush()
    live = w.live
    m = live.manifest()
    assert len(m["segments"]) >= 2  # auto-flush actually segmented
    assert live.doc_counts() == {"total": 10, "tombstoned": 0,
                                 "live": 10}
    # add of an existing docid is loud; update upserts; delete is
    # idempotent
    with pytest.raises(ValueError):
        w.add("D-000", "dup")
    w.update("D-000", "brand new text")
    assert w.delete("D-001") is True
    assert w.delete("NOPE") is False
    w.flush()
    m = live.manifest()
    tombs = m["tombstones"]
    # both the updated and the deleted doc are tombstoned in their
    # ORIGINAL segment; the update's new copy lives in the new segment
    assert sum(len(t) for t in tombs.values()) == 2
    dm = live.live_doc_map()
    assert "D-001" not in dm
    assert dm["D-000"] == m["segments"][-1]
    assert live.doc_counts()["live"] == 9
    # markup that would corrupt the TREC framing is rejected at add()
    with pytest.raises(ValueError):
        w.add("bad id", "text")
    with pytest.raises(ValueError):
        w.add("OK-1", "sneaky </TEXT> closer")


def test_crash_safe_commit_and_gc(tmp_path):
    """A segment dir without metadata (a crashed build) is never
    referenced and gc removes it with the stale generations."""
    live_dir = str(tmp_path / "live")
    live = LiveIndex.create(live_dir, num_shards=N_SHARDS)
    rng = random.Random(1)
    w = IngestWriter(live_dir, auto_merge=False)
    for i in range(6):
        w.add(f"D-{i:03d}", make_text(rng))
        w.flush()  # one generation per doc: a long chain to prune
    # simulate a crashed segment build: dir exists, no metadata
    orphan = live.segment_path("seg-999999")
    os.makedirs(orphan)
    out = live.gc(keep_generations=2)
    assert "seg-999999" in out["dropped_segments"]
    assert live.generations() == out["kept_generations"]
    # everything the kept manifests reference is still loadable
    kept = set()
    for g in live.generations():
        kept.update(live.manifest(g)["segments"])
    for name in kept:
        fmt.IndexMetadata.load(live.segment_path(name))
    # the crashed-name slot is never reused for different content
    assert live._next_segment_name(live.manifest()) != "seg-999999"


# ---------------------------------------------------------------------------
# bit-identity: drop_docs, compaction, fuzz, merge orders
# ---------------------------------------------------------------------------


def test_drop_docs_bit_identical(tmp_path):
    rng = random.Random(2)
    docs = {f"D-{i:03d}": make_text(rng) for i in range(9)}
    src = scratch_build(tmp_path, docs, "src")
    src_dir = str(tmp_path / "src")
    dropped = ["D-001", "D-004", "D-008"]
    out_dir = str(tmp_path / "dropped")
    meta = drop_docs(src_dir, out_dir, dropped)
    survivors = {d: t for d, t in docs.items() if d not in dropped}
    oracle = scratch_build(tmp_path, survivors, "oracle")
    assert_bit_identical(oracle, meta)
    del src
    # loud failure modes: unknown docid, dropping everything
    with pytest.raises(ValueError):
        drop_docs(src_dir, str(tmp_path / "x1"), ["GHOST"])
    with pytest.raises(ValueError):
        drop_docs(src_dir, str(tmp_path / "x2"), list(docs))


@pytest.mark.parametrize("seed", [3, 4])
def test_compact_bit_identical_fuzz(tmp_path, seed):
    """THE acceptance pin: random add/update/delete sequences across
    random flush boundaries; full compaction == from-scratch build of
    the surviving docs, metadata checksums equal."""
    rng = random.Random(seed)
    live_dir = str(tmp_path / f"live{seed}")
    LiveIndex.create(live_dir, num_shards=N_SHARDS)
    surviving: dict = {}
    w = IngestWriter(live_dir, buffer_docs=64, auto_merge=False)
    next_id = 0
    for _ in range(28):
        op = rng.random()
        if op < 0.55 or not surviving:
            d = f"D-{next_id:03d}"
            next_id += 1
            t = make_text(rng)
            w.add(d, t)
            surviving[d] = t
        elif op < 0.8:
            d = rng.choice(sorted(surviving))
            t = make_text(rng)
            w.update(d, t)
            surviving[d] = t
        else:
            d = rng.choice(sorted(surviving))
            w.delete(d)
            del surviving[d]
        if rng.random() < 0.25:
            w.flush()
    m = w.compact_all()
    assert len(m["segments"]) == 1 and not m["tombstones"]
    sdir, gen = resolve_serving(live_dir)
    meta = fmt.IndexMetadata.load(sdir)
    oracle = scratch_build(tmp_path, surviving, f"oracle{seed}")
    assert_bit_identical(oracle, meta)
    assert latest_servable(live_dir) == (sdir, gen)


def test_merge_order_independent(tmp_path):
    """Pairwise compaction in either association order produces the
    SAME bytes as one-shot compaction — the merge-orders half of the
    acceptance pin."""
    rng = random.Random(5)
    metas = []
    for variant in ("all", "left", "right"):
        live_dir = str(tmp_path / f"live-{variant}")
        LiveIndex.create(live_dir, num_shards=N_SHARDS)
        w = IngestWriter(live_dir, buffer_docs=1000, auto_merge=False)
        rng_v = random.Random(5)  # identical op stream per variant
        for i in range(12):
            w.add(f"D-{i:03d}", make_text(rng_v))
            if i % 4 == 3:
                w.flush()
        w.delete("D-002")
        w.update("D-005", "fresh text for five")
        w.flush()
        live = w.live
        segs = live.manifest()["segments"]
        assert len(segs) >= 3
        if variant == "all":
            compact(live)
        elif variant == "left":
            compact(live, segs[:2])
            compact(live)
        else:
            compact(live, segs[-2:])
            compact(live)
        sdir, _ = resolve_serving(live_dir)
        metas.append(fmt.IndexMetadata.load(sdir))
    assert_bit_identical(metas[0], metas[1])
    assert_bit_identical(metas[0], metas[2])


def test_fully_tombstoned_segment_is_dropped(tmp_path):
    live_dir = str(tmp_path / "live")
    LiveIndex.create(live_dir, num_shards=N_SHARDS)
    rng = random.Random(6)
    w = IngestWriter(live_dir, auto_merge=False)
    for i in range(3):
        w.add(f"A-{i}", make_text(rng))
    w.flush()
    doomed = w.live.manifest()["segments"][0]
    for i in range(3):
        w.add(f"B-{i}", make_text(rng))
    w.flush()
    for i in range(3):
        w.delete(f"A-{i}")
    w.flush()
    m = compact(w.live, [doomed])
    # the dead segment left the set without a merge minting a new one
    assert doomed not in m["segments"]
    assert w.live.doc_counts()["live"] == 3
    m = compact(w.live)
    sdir, _ = resolve_serving(live_dir)
    assert fmt.IndexMetadata.load(sdir).num_docs == 3


# ---------------------------------------------------------------------------
# merge policy
# ---------------------------------------------------------------------------


def test_plan_merges_tier_policy():
    def manifest(docs, tombs=None):
        return {"segments": list(docs), "docs": docs,
                "tombstones": tombs or {}}

    # under factor: no debt
    assert plan_merges(manifest({"a": 10, "b": 12}),
                       factor=4, tier_ratio=8.0) == []
    # four small segments in one tier: one group, manifest order
    m = manifest({"a": 5, "b": 6, "c": 7, "d": 7, "big": 5000})
    assert plan_merges(m, factor=4, tier_ratio=8.0) == [
        ["a", "b", "c", "d"]]
    # a half-dead segment joins the indebted group even off-tier
    m = manifest({"a": 5, "b": 6, "c": 7, "d": 7, "big": 5000},
                 {"big": [f"D{i}" for i in range(2600)]})
    (group,) = plan_merges(m, factor=4, tier_ratio=8.0)
    assert "big" in group
    # a lone half-dead segment still compacts (reclamation)
    m = manifest({"big": 100}, {"big": [f"D{i}" for i in range(60)]})
    assert plan_merges(m, factor=4, tier_ratio=8.0) == [["big"]]
    # merge_debt mirrors the plan
    debt = merge_debt(m)
    assert debt["pending_merge_groups"] == [["big"]]
    assert debt["live_doc_fraction"] == 0.4


def test_auto_merge_bounds_segment_count(tmp_path):
    """With auto_merge on, the tiered policy keeps the segment count
    bounded while flushes keep landing."""
    live_dir = str(tmp_path / "live")
    LiveIndex.create(live_dir, num_shards=N_SHARDS)
    rng = random.Random(7)
    w = IngestWriter(live_dir, buffer_docs=1000, auto_merge=True)
    peak = 0
    for i in range(7):
        for j in range(2):
            w.add(f"D-{i:02d}-{j}", make_text(rng))
        w.flush()
        peak = max(peak, len(w.live.manifest()["segments"]))
    factor = 4  # the TPU_IR_MERGE_FACTOR default
    assert peak <= factor, (
        f"auto-merge let {peak} segments accumulate past the factor")


# ---------------------------------------------------------------------------
# verify / doctor / CLI surfaces
# ---------------------------------------------------------------------------


@pytest.fixture()
def small_live(tmp_path):
    live_dir = str(tmp_path / "live")
    LiveIndex.create(live_dir, num_shards=N_SHARDS)
    rng = random.Random(8)
    w = IngestWriter(live_dir, auto_merge=False)
    for i in range(6):
        w.add(f"D-{i:03d}", make_text(rng))
    w.flush()
    for i in range(3):
        w.add(f"E-{i:03d}", make_text(rng))
    w.delete("D-001")
    w.flush()
    return live_dir


def test_serving_follows_latest_servable(small_live):
    """An uncompacted HEAD generation is normal between flushes: the
    default (gen=None) resolution falls back to the newest SERVABLE
    generation instead of killing a worker spawn/reload/router start;
    an EXPLICIT uncompacted generation still raises with the recipe."""
    live = LiveIndex.open(small_live)
    head = live.current_gen()
    sdir, gen = resolve_serving(small_live)
    assert gen < head  # the head (2 segments + tombstone) was skipped
    assert (sdir, gen) == latest_servable(small_live)
    fmt.IndexMetadata.load(sdir)  # actually loadable
    with pytest.raises(ValueError):
        resolve_serving(small_live, head)  # explicit stays strict


def test_verify_live(small_live):
    from tpu_ir import faults
    from tpu_ir.index.verify import verify_live

    out = verify_live(small_live)
    assert out["ok"] and out["live"]
    assert out["num_segments"] == 2
    assert out["num_docs"] == 8 and out["tombstoned"] == 1
    # a tombstone naming a doc its segment never indexed is corruption
    live = LiveIndex.open(small_live)
    m = live.manifest()
    m["tombstones"] = {m["segments"][0]: ["GHOST-DOC"]}
    live.commit(m["segments"], m["tombstones"], m["docs"], note="bad")
    with pytest.raises(faults.IntegrityError):
        verify_live(small_live)


def test_doctor_live_topology(small_live):
    from tpu_ir.index.doctor import doctor_report

    report = doctor_report(small_live)
    assert report["live"] is True
    assert report["segment_count"] == 2
    kinds = {s["kind"] for s in report["segments"]}
    assert kinds == {"base", "delta"}
    assert report["docs"] == {"total": 9, "tombstoned": 1, "live": 8}
    assert report["base_bytes"] > 0 and report["delta_bytes"] > 0
    assert 0 < report["live_doc_fraction"] < 1
    assert "merge_debt" in report
    # multi-segment + tombstones => the not-directly-servable warning
    assert any("not directly servable" in w for w in report["warnings"])


def test_cli_verify_and_doctor_route_live(small_live, capsys):
    from tpu_ir.cli import main

    assert main(["verify", small_live]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["live"] and out["ok"]
    assert main(["doctor", small_live]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["live"] and out["segment_count"] == 2


def test_cli_ingest_and_generations(tmp_path, capsys):
    from tpu_ir.cli import main

    corpus = write_trec(tmp_path / "c.trec",
                        {f"D-{i}": make_text(random.Random(9))
                         for i in range(5)})
    live_dir = str(tmp_path / "live")
    rc = main(["ingest", live_dir, "--init", "--add", corpus,
               "--shards", str(N_SHARDS)])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["added"] == 5 and out["live"] == 5
    assert out["generation"] >= 1
    rc = main(["ingest", live_dir, "--delete", "D-1", "--compact"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["deleted"] == 1 and out["live"] == 4
    assert len(out["segments"]) == 1
    rc = main(["generations", live_dir])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["current"] == max(e["gen"] for e in out["generations"])
    assert out["generations"][-1]["servable"] is True
    # adding an existing docid is the loud error path (exit 1, message)
    rc = main(["ingest", live_dir, "--add", corpus])
    assert rc == 1


def test_ingest_counters_and_gauges_declared(small_live):
    from tpu_ir import obs
    from tpu_ir.obs.registry import (
        DECLARED_COUNTERS,
        DECLARED_GAUGES,
        DECLARED_HISTOGRAMS,
    )

    for name in ("ingest.docs_added", "ingest.flushes", "merge.runs",
                 "merge.docs_dropped", "generation.commits",
                 "router.mixed_generation"):
        assert name in DECLARED_COUNTERS
    for name in ("ingest.flush", "merge.run", "generation.swap"):
        assert name in DECLARED_HISTOGRAMS
    for name in ("generation.current", "generation.segments",
                 "generation.tombstones"):
        assert name in DECLARED_GAUGES
    # the fixture's ingest actually moved the ledgers
    reg = obs.get_registry()
    assert reg.get("ingest.docs_added") == 9
    assert reg.get("ingest.docs_deleted") == 1
    assert reg.get("ingest.flushes") == 2
    assert reg.get("generation.commits") == 2
    assert reg.get_gauge("generation.current") == 2
