"""CLI surface tests: index / search / inspect / verify / pack / expand."""

import json
import os

import pytest

from tpu_ir.cli import main

DOCS = {
    "D-01": "alpha bravo charlie delta",
    "D-02": "alpha alpha echo foxtrot",
    "D-03": "charlie golf hotel india bravo",
}


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("cli")
    corpus = tmp / "corpus.trec"
    corpus.write_text("".join(
        f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
        for d, t in DOCS.items()))
    index_dir = str(tmp / "index")
    rc = main(["index", str(corpus), index_dir, "--shards", "2"])
    assert rc == 0
    # a live index for the `generations` smoke row (built eagerly so the
    # alphabetically-earlier parametrized run finds it populated)
    rc = main(["ingest", str(tmp / "live"), "--init", "--add",
               str(corpus), "--shards", "2", "--compact"])
    assert rc == 0
    return str(corpus), index_dir, tmp


def test_index_and_verify(setup, capsys):
    _, index_dir, _ = setup
    assert main(["verify", index_dir]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["ok"] and out["num_docs"] == 3


def test_search_query(setup, capsys):
    _, index_dir, _ = setup
    assert main(["search", index_dir, "-q", "alpha"]) == 0
    out = capsys.readouterr().out
    assert "D-02" in out and "D-01" in out
    # D-02 has tf=2 for alpha -> ranks first
    assert out.index("D-02") < out.index("D-01")


def test_search_batch_file(setup, capsys, tmp_path):
    _, index_dir, _ = setup
    qf = tmp_path / "queries.txt"
    qf.write_text("alpha\ncharlie bravo\n")
    assert main(["search", index_dir, "--queries-file", str(qf)]) == 0
    out = capsys.readouterr().out
    assert out.count("query:") == 2


def test_inspect(setup, capsys):
    _, index_dir, _ = setup
    assert main(["inspect", index_dir, "-n", "5"]) == 0
    out = capsys.readouterr().out
    assert "part-0000" in out and "df=" in out


def test_expand(setup, capsys):
    _, index_dir, _ = setup
    assert main(["expand", index_dir, "al*", "--chargram-k", "2"]) == 0
    out = capsys.readouterr().out.split()
    assert "alpha" in out


def test_pack_roundtrip(setup, capsys, tmp_path):
    txt = tmp_path / "lines.txt"
    txt.write_text("first document line\nsecond line here\n")
    trec = tmp_path / "packed.trec"
    assert main(["pack", str(txt), str(trec), "--prefix", "L"]) == 0
    idx = str(tmp_path / "packed_index")
    assert main(["index", str(trec), idx, "--no-chargrams"]) == 0
    assert main(["verify", idx]) == 0
    out = capsys.readouterr().out
    meta = json.loads(out.strip().splitlines()[-1])
    assert meta["num_docs"] == 2


def test_verify_catches_corruption(setup, tmp_path):
    import numpy as np

    from tpu_ir.index import build_index
    from tpu_ir.index import format as fmt
    from tpu_ir.index.verify import verify_index

    corpus, _, _ = setup
    idx = str(tmp_path / "corrupt")
    build_index([corpus], idx, num_shards=2, compute_chargrams=False)
    z = fmt.load_shard(idx, 0)
    z["pair_tf"] = z["pair_tf"].copy()
    # precondition, not a silent skip: an empty shard 0 would make this
    # test verify nothing (review r5)
    assert len(z["pair_tf"])
    z["pair_tf"][0] = 0  # invalid tf
    fmt.save_shard(idx, 0, **{k: z[k] for k in
                              ["term_ids", "indptr", "pair_doc",
                               "pair_tf", "df"]})
    with pytest.raises(AssertionError):
        verify_index(idx)


def test_count(setup, capsys):
    corpus, _, _ = setup
    assert main(["count", corpus]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["Count.DOCS"] == 3
    assert out["min_docid"] == "D-01" and out["max_docid"] == "D-03"


def test_verify_catches_chargram_and_doclen_corruption(setup, tmp_path):
    """Other artifact families: a shuffled char-gram term list and a
    wrong-length doclen must both fail verification."""
    import numpy as np

    from tpu_ir.index import build_index
    from tpu_ir.index import format as fmt
    from tpu_ir.index.verify import verify_index

    corpus, _, _ = setup
    idx = str(tmp_path / "corrupt2")
    build_index([corpus], idx, num_shards=2, chargram_ks=[2])
    assert verify_index(idx)["ok"]

    # chargram: reverse one gram's term list (must be sorted-unique)
    z = fmt.load_chargram(idx, 2)
    tids = z["term_ids"].copy()
    lo, hi = None, None
    for g in range(len(z["gram_codes"])):
        if z["indptr"][g + 1] - z["indptr"][g] >= 2:
            lo, hi = int(z["indptr"][g]), int(z["indptr"][g + 1])
            break
    assert lo is not None, "need a gram with >= 2 terms"
    tids[lo:hi] = tids[lo:hi][::-1]
    fmt.save_chargram(idx, 2, gram_codes=z["gram_codes"],
                      indptr=z["indptr"], term_ids=tids)
    with pytest.raises(AssertionError):
        verify_index(idx)
    fmt.save_chargram(idx, 2, **{k: z[k] for k in z})  # restore

    # doclen: truncate
    import os

    dl = np.load(os.path.join(idx, fmt.DOCLEN))
    np.save(os.path.join(idx, fmt.DOCLEN), dl[:-1])
    with pytest.raises(AssertionError):
        verify_index(idx)


def test_docno_cli(setup, capsys):
    """TrecDocnoMapping CLI parity: list / getDocno / getDocid
    (TrecDocnoMapping.java:164-200)."""
    from tpu_ir.cli import main

    _, idx, _ = setup
    assert main(["docno", idx, "list"]) == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert lines and all("\t" in l for l in lines)
    # reference column order: docno first ("i + \"\\t\" + mDocids[i]")
    docno, docid = lines[0].split("\t")
    assert docno == "1"

    assert main(["docno", idx, "getDocno", docid]) == 0
    assert capsys.readouterr().out.strip() == docno
    assert main(["docno", idx, "getDocid", docno]) == 0
    assert capsys.readouterr().out.strip() == docid
    assert main(["docno", idx, "getDocno", "NO-SUCH-DOC"]) == 1
    assert main(["docno", idx, "getDocid", "999999"]) == 1
    assert main(["docno", idx, "getDocid", "not-a-number"]) == 1
    # missing positional arg is a usage error, not a crash
    assert main(["docno", idx, "getDocno"]) == 1
    assert main(["docno", idx, "getDocid"]) == 1

def test_inspect_term(setup, capsys):
    """Per-term random access through dictionary.tsv — the reference
    getValue seek path (IntDocVectorsForwardIndex.java:148-184) finally has
    a consumer."""
    _, index_dir, _ = setup
    assert main(["inspect", index_dir, "--term", "alpha"]) == 0
    out = capsys.readouterr().out
    assert "df=2" in out and "alpha" in out
    # input is analyzed like a query (case folding, punctuation)
    assert main(["inspect", index_dir, "--term", "Alpha,"]) == 0
    assert "df=2" in capsys.readouterr().out
    assert main(["inspect", index_dir, "--term", "zzznope"]) == 1


def test_dictionary_access(setup):
    from tpu_ir.index.dictionary import Dictionary, verify_dictionary_access

    _, index_dir, _ = setup
    d = Dictionary(index_dir)
    tp = d.get_value("alpha")
    assert tp is not None and tp.df == 2
    # postings in reference order: tf desc (D-02 has tf=2), doc asc
    assert tp.postings[0, 1] == 2
    assert d.get_value("no-such-term") is None  # miss -> None (ref null)
    assert verify_dictionary_access(index_dir) > 0


def test_dictionary_detects_tamper(setup, tmp_path):
    """The post-seek term-match check (reference :175-179): a dictionary
    line pointing at the wrong offset must raise, not silently return the
    wrong postings."""
    import shutil

    from tpu_ir.index import format as fmt
    from tpu_ir.index.dictionary import Dictionary

    _, index_dir, _ = setup
    bad = tmp_path / "bad-index"
    shutil.copytree(index_dir, bad)
    path = os.path.join(bad, fmt.DICTIONARY)
    lines = open(path).read().splitlines()
    # swap the offsets of two same-shard terms
    t0, s0, o0 = lines[0].rsplit("\t", 2)
    swap = next(i for i, l in enumerate(lines[1:], 1)
                if l.rsplit("\t", 2)[1] == s0
                and l.rsplit("\t", 2)[2] != o0)
    ts, ss, os_ = lines[swap].rsplit("\t", 2)
    lines[0] = f"{t0}\t{s0}\t{os_}"
    lines[swap] = f"{ts}\t{ss}\t{o0}"
    open(path, "w").write("\n".join(lines) + "\n")
    with pytest.raises(AssertionError):
        Dictionary(str(bad)).get_value(t0)


def test_dictionary_term_with_unicode_line_separator():
    """splitlines() also breaks on U+0085/U+2028, which the analyzer
    allows INSIDE a token — a NEL-bearing term must parse as one
    dictionary line or every later term id shifts (review r5)."""
    from tpu_ir.index.dictionary import Dictionary

    text = "ab\x85cd\t0\t0\nzz\t1\t4\n"
    d = Dictionary(".", text=text)
    assert len(d) == 2
    assert "ab\x85cd" in d and "zz" in d


def test_eval_default_skips_zero_relevant_topics(tmp_path, capsys):
    """A topic judged ONLY nonrelevant contributes no mean term in the
    DEFAULT mode too — trec_eval skips num_rel==0 topics, and scoring
    them 0 deflated every metric (review r5)."""
    run = tmp_path / "run.txt"
    run.write_text("1 Q0 D-1 1 2.0 t\n2 Q0 D-9 1 2.0 t\n")
    qrels = tmp_path / "qrels.txt"
    qrels.write_text("1 0 D-1 1\n2 0 D-9 0\n")
    assert main(["eval", str(run), str(qrels)]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["queries"] == 1
    assert out["map"] == 1.0 and out["mrr"] == 1.0


def test_warm_prebuilds_serving_cache(setup, capsys, tmp_path):
    """tpu-ir warm: one deploy-time load persists the serving cache; the
    second load inside the command must already take the fast path."""
    corpus = tmp_path / "c.trec"
    corpus.write_text(
        "<DOC>\n<DOCNO> A-1 </DOCNO>\n<TEXT>\nsalmon river fishing\n"
        "</TEXT>\n</DOC>\n"
        "<DOC>\n<DOCNO> A-2 </DOCNO>\n<TEXT>\ntrout river\n</TEXT>\n</DOC>\n")
    idx = str(tmp_path / "idx")
    assert main(["index", str(corpus), idx, "--no-chargrams"]) == 0
    assert main(["warm", idx]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["cache_written"] is True
    assert out["warm_skips_shards"] is True
    assert os.path.isdir(os.path.join(idx, "serving-tiered"))


def test_trec_run_output(setup, capsys, tmp_path):
    """--trec-run emits standard trec_eval lines: qid Q0 docid rank score
    tag, 1-based qids in query-file order."""
    corpus = tmp_path / "c.trec"
    corpus.write_text(
        "<DOC>\n<DOCNO> A-1 </DOCNO>\n<TEXT>\nsalmon river\n</TEXT>\n</DOC>\n"
        "<DOC>\n<DOCNO> A-2 </DOCNO>\n<TEXT>\ntrout river\n</TEXT>\n</DOC>\n")
    idx = str(tmp_path / "idx")
    assert main(["index", str(corpus), idx, "--no-chargrams"]) == 0
    qf = tmp_path / "q.txt"
    # note: 'river' would return nothing (df == N -> idf 0, the documented
    # zero-score deviation) — use discriminative terms
    qf.write_text("salmon\nsalmon trout\n")
    capsys.readouterr()
    assert main(["search", idx, "--queries-file", str(qf),
                 "--trec-run", "run1"]) == 0
    lines = [l.split() for l in capsys.readouterr().out.strip().splitlines()]
    assert all(len(l) == 6 and l[1] == "Q0" and l[5] == "run1"
               for l in lines)
    assert lines[0][:3] == ["1", "Q0", "A-1"]      # qid 1 = 'salmon'
    q2 = [l for l in lines if l[0] == "2"]          # hits both docs
    assert {l[2] for l in q2} == {"A-1", "A-2"}
    assert [l[3] for l in q2] == ["1", "2"]         # ranks ascend


def test_topics_file_with_trec_run(setup, capsys, tmp_path):
    """TREC topics input: <num>/<title> records drive the batch, topic
    numbers become the run qids (classic multi-line and one-line shapes)."""
    corpus = tmp_path / "c.trec"
    corpus.write_text(
        "<DOC>\n<DOCNO> A-1 </DOCNO>\n<TEXT>\nsalmon river\n</TEXT>\n</DOC>\n"
        "<DOC>\n<DOCNO> A-2 </DOCNO>\n<TEXT>\ntrout stream\n</TEXT>\n</DOC>\n")
    idx = str(tmp_path / "idx")
    assert main(["index", str(corpus), idx, "--no-chargrams"]) == 0
    topics = tmp_path / "topics.txt"
    topics.write_text(
        "<top>\n<num> Number: 301\n<title> salmon\n\n<desc> Description:\n"
        "x\n</top>\n"
        "<top>\n<num> Number: 302\n<title>trout</title>\n</top>\n")
    capsys.readouterr()
    assert main(["search", idx, "--topics", str(topics),
                 "--trec-run", "r"]) == 0
    lines = [l.split() for l in capsys.readouterr().out.strip().splitlines()]
    assert [l[0] for l in lines] == ["301", "302"]
    assert [l[2] for l in lines] == ["A-1", "A-2"]


def test_eval_run_against_qrels(setup, capsys, tmp_path):
    """End-to-end eval loop: topics -> --trec-run run file -> tpu-ir eval
    against qrels, metrics hand-checked."""
    run = tmp_path / "run.txt"
    # q1: relevant doc at rank 2; q2: relevant at rank 1 (of 2 relevant,
    # one never retrieved); q3 unjudged (excluded per trec_eval convention)
    run.write_text(
        "1 Q0 D-9 1 3.0 t\n1 Q0 D-1 2 2.0 t\n"
        "2 Q0 D-2 1 2.5 t\n2 Q0 D-8 2 1.0 t\n"
        "3 Q0 D-5 1 1.0 t\n")
    qrels = tmp_path / "qrels.txt"
    qrels.write_text(
        "1 0 D-1 1\n1 0 D-7 0\n"
        "2 0 D-2 2\n2 0 D-3 1\n")
    assert main(["eval", str(run), str(qrels)]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["queries"] == 2
    # q1: AP = (1/2)/1 = 0.5, RR = 0.5; q2: AP = (1/1)/2 = 0.5, RR = 1.0
    assert out["map"] == pytest.approx(0.5)
    assert out["mrr"] == pytest.approx(0.75)
    # q1 NDCG@10: rel grade 1 at rank 2 -> (1/log2(3)) / ideal(1/log2(2))
    import math
    q1 = (1 / math.log2(3)) / 1.0
    # q2: grade-2 doc at rank 1; ideal = 2/log2(2) + 1/log2(3)
    q2 = 2.0 / (2.0 + 1 / math.log2(3))
    assert out["ndcg_at_10"] == pytest.approx(round((q1 + q2) / 2, 4), abs=1e-4)
    assert out["p_at_5"] == pytest.approx(0.2)       # 1/5 each query
    assert out["recall_at_100"] == pytest.approx(0.75)  # 1.0 and 0.5

    # empty intersection -> exit 1
    bad = tmp_path / "bad.txt"
    bad.write_text("9 0 D-1 1\n")
    assert main(["eval", str(run), str(bad)]) == 1


def test_eval_skips_malformed_lines(tmp_path, capsys):
    """Run/qrels readers tolerate malformed lines (short rows, non-numeric
    ranks/grades) by skipping them, like trec_eval."""
    run = tmp_path / "run.txt"
    run.write_text("garbage\n1 Q0 D-1 notanint 1.0 t\n"
                   "1 Q0 D-1 1 2.0 t\nshort row\n")
    qrels = tmp_path / "qrels.txt"
    qrels.write_text("1 0 D-1 one\n1 0 D-1 1\nbad\n")
    from tpu_ir.cli import main
    assert main(["eval", str(run), str(qrels)]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["queries"] == 1 and out["map"] == 1.0


def test_eval_complete_scores_missing_qids_zero(tmp_path, capsys):
    """--complete (trec_eval -c): average over EVERY qrels qid; a judged
    query absent from the run scores zero instead of being excluded."""
    run = tmp_path / "run.txt"
    run.write_text("1 Q0 D-1 1 2.0 t\n")   # q2 judged but never retrieved
    qrels = tmp_path / "qrels.txt"
    # q3 is judged but has NO relevant docs: trec_eval skips num_rel==0
    # topics even under -c, so it must not drag the -c average down
    qrels.write_text("1 0 D-1 1\n2 0 D-2 1\n3 0 D-9 0\n")
    assert main(["eval", str(run), str(qrels)]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["queries"] == 1 and out["map"] == 1.0  # default: q2 excluded
    assert main(["eval", str(run), str(qrels), "--complete"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["queries"] == 2
    assert out["map"] == pytest.approx(0.5)  # q2 contributes 0, not nothing
    assert out["mrr"] == pytest.approx(0.5)


def test_repl_trec_run_qids_advance(setup, capsys, monkeypatch):
    """Interactive stdin search with --trec-run must number queries with a
    running qid — not reset to 1 per line (which would merge every query
    into one qid downstream in eval)."""
    _, index_dir, _ = setup
    lines = iter(["alpha", "charlie", "exit"])
    monkeypatch.setattr("builtins.input", lambda *_: next(lines))
    assert main(["search", index_dir, "--trec-run", "repl"]) == 0
    out = capsys.readouterr().out
    qids = {ln.split()[0] for ln in out.splitlines()
            if ln.endswith(" repl")}
    assert qids == {"1", "2"}


# ---------------------------------------------------------------------------
# the CLI smoke matrix (ISSUE 8 satellite): EVERY tpu-ir subcommand runs
# against a tiny fixture index, exits 0, and (where the command's contract
# is JSON) emits schema-checked JSON. The matrix is pinned complete
# against the parser source, so a future subcommand cannot ship without a
# direct invocation test.
# ---------------------------------------------------------------------------


def _smoke_matrix(index_dir: str, corpus: str, tmp) -> dict:
    """{subcommand: (argv, required-JSON-keys | None)}; None = text/
    human output, only the exit code is the contract."""
    run = tmp / "smoke_run.txt"
    run.write_text("1 Q0 D-1 1 2.0 t\n")
    qrels = tmp / "smoke_qrels.txt"
    qrels.write_text("1 0 D-1 1\n")
    lines = tmp / "smoke_lines.txt"
    lines.write_text("one line\n")
    return {
        "index": (["index", corpus, str(tmp / "smoke_idx"),
                   "--no-chargrams"], {"num_docs"}),
        "ingest": (["ingest", str(tmp / "smoke_live"), "--init",
                    "--add", corpus, "--shards", "2", "--compact"],
                   {"generation", "live", "segments", "added"}),
        "generations": (["generations", str(tmp / "live")],
                        {"current", "generations"}),
        "search": (["search", index_dir, "-q", "alpha"], None),
        "inspect": (["inspect", index_dir, "-n", "2"], None),
        "verify": (["verify", index_dir], {"ok"}),
        "migrate-index": (["migrate-index", index_dir, "--to", "2"],
                          {"ok", "format_version"}),
        "warm": (["warm", index_dir], {"cache_written", "warm_load_s"}),
        "merge": (["merge", index_dir, str(tmp / "smoke_merged"),
                   "--no-chargrams"], {"num_docs"}),
        "stats": (["stats"], {"recovery", "serving", "histograms"}),
        "metrics": (["metrics"], {"counters", "histograms", "schema"}),
        "trace-dump": (["trace-dump", "--out",
                        str(tmp / "smoke_dump.jsonl")],
                       {"traces", "out"}),
        "profile": (["profile"], {"functions", "dispatch", "gauges"}),
        "querylog": (["querylog"],
                     {"ring", "entries", "slow_entries", "recorded"}),
        "trace": (["trace"], {"traces"}),
        "doctor": (["doctor", index_dir],
                   {"metadata", "df", "shards", "tiers", "warnings"}),
        "bench-check": (["bench-check", "--self-test"], {"status"}),
        "serve-bench": (["serve-bench", index_dir, "--threads", "2",
                         "--queries", "8", "--deadline", "5.0"],
                        {"submitted", "served", "shed", "latency",
                         "querylog"}),
        "cache": (["cache"], {"counters", "caches"}),
        "scale": (["scale"], {"enabled", "config"}),
        "top": (["top", "--json"], {"enabled", "tiers", "series"}),
        "compact": (["compact", str(tmp / "live")],
                    {"steps", "segments", "generation", "mode"}),
        "backup": (["backup", str(tmp / "live"),
                    str(tmp / "smoke_backup")],
                   {"generation", "segments", "files", "dest"}),
        "serve-worker": (["serve-worker", index_dir, "--shard", "0/2",
                          "--no-warm", "--run-for", "0.05"],
                         {"addr", "shard", "num_shards", "doc_range"}),
        "eval": (["eval", str(run), str(qrels)], {"map", "queries"}),
        "pack": (["pack", str(lines), str(tmp / "smoke_packed.trec")],
                 {"docs_packed"}),
        "count": (["count", corpus], {"Count.DOCS"}),
        "docno": (["docno", index_dir, "list"], None),
        "expand": (["expand", index_dir, "al*", "--chargram-k", "2"],
                   None),
        "lint": (["lint"], None),
    }


# ONE name list drives both the parametrization and the completeness
# pin — a new subcommand without a matrix row (or a matrix row without
# a parametrized run) fails below instead of silently never smoking
_SMOKE_NAMES = sorted(
    ["index", "search", "inspect", "verify", "migrate-index", "warm",
     "merge", "stats", "metrics", "trace-dump", "profile", "querylog",
     "doctor", "bench-check", "serve-bench", "eval", "pack", "count",
     "docno", "expand", "lint", "ingest", "generations", "cache",
     "compact", "serve-worker", "scale", "backup", "trace", "top"])


def test_cli_smoke_matrix_is_complete(setup):
    """Every subcommand the parser registers has a matrix row AND a
    parametrized smoke run (the two lists cannot drift apart)."""
    import re as _re

    import tpu_ir.cli as cli_mod

    src = open(cli_mod.__file__, encoding="utf-8").read()
    registered = set(_re.findall(r'sub\.add_parser\(\s*"([\w-]+)"', src))
    corpus, index_dir, tmp = setup
    matrix = _smoke_matrix(index_dir, corpus, tmp)
    assert set(matrix) == registered, (
        "CLI smoke matrix drifted from the registered subcommands: "
        f"missing {registered - set(matrix)}, "
        f"stale {set(matrix) - registered}")
    assert set(_SMOKE_NAMES) == set(matrix), (
        "the parametrized name list drifted from the matrix: "
        f"{set(_SMOKE_NAMES) ^ set(matrix)}")


@pytest.mark.parametrize("name", _SMOKE_NAMES)
def test_cli_smoke(setup, capsys, tmp_path, name):
    corpus, index_dir, tmp = setup
    argv, keys = _smoke_matrix(index_dir, corpus, tmp)[name]
    assert main(argv) == 0, name
    out = capsys.readouterr().out
    if keys is not None:
        payload = json.loads(out.strip().splitlines()[-1])
        missing = keys - set(payload)
        assert not missing, (name, missing, sorted(payload))
