"""Streaming-build crash resume (VERDICT r2 item 3): the pass DAG inside
one streaming job resumes from its last complete artifact — pass-1 token
spills, per-batch pass-2 pair spills, per-shard pass-3 part files — the
reference's resume-by-artifact idea (BuildIntDocVectorsForwardIndex.java:
186-194) generalized per SURVEY §5. A restart after a crash must produce
byte-identical artifacts WITHOUT re-tokenizing, and stale spills from a
different config must be discarded, not trusted."""

import filecmp
import os

import numpy as np
import pytest

import tpu_ir.index.streaming as streaming
from tpu_ir.index import format as fmt
from tpu_ir.index.streaming import PASS1_MANIFEST, build_index_streaming
from tpu_ir.index.verify import verify_index
from tpu_ir.search import Scorer

WORDS = ("salmon fishing river bears honey quick brown fox lazy dog "
         "market investor asset bond stock season rain forest".split())


def write_corpus(path, n_docs=120, skew=0):
    body = []
    for i in range(n_docs):
        text = " ".join(WORDS[(i + j + skew) % len(WORDS)]
                        for j in range(3 + (i % 7)))
        body.append(f"<DOC>\n<DOCNO> D-{i:04d} </DOCNO>\n<TEXT>\n"
                    f"{text}\n</TEXT>\n</DOC>\n")
    path.write_text("".join(body))
    return str(path)


def artifact_names(d):
    return sorted(
        n for n in os.listdir(d)
        if not n.startswith(".") and n != fmt.JOBS_DIR
        and not n.startswith("serving-"))


def assert_identical(got_dir, want_dir):
    names = artifact_names(want_dir)
    assert artifact_names(got_dir) == names
    for n in names:
        assert filecmp.cmp(os.path.join(want_dir, n),
                           os.path.join(got_dir, n), shallow=False), n


BUILD_KW = dict(k=1, num_shards=3, batch_docs=25, chargram_ks=[2])


@pytest.fixture(scope="module")
def ref(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("stream_resume")
    corpus = write_corpus(tmp / "corpus.trec")
    ref_dir = str(tmp / "ref")
    build_index_streaming([corpus], ref_dir, **BUILD_KW)
    return corpus, ref_dir


def forbid_tokenizer(monkeypatch):
    def boom(*a, **kw):
        raise AssertionError("resume must not re-tokenize the corpus")
    monkeypatch.setattr(streaming, "make_chunked_tokenizer", boom)


_REAL_TOKENIZER = streaming.make_chunked_tokenizer


def small_chunks(monkeypatch):
    """Tiny read chunks so the 120-doc corpus spans several spill batches
    (batch flush granularity is one tokenizer delta)."""
    monkeypatch.setattr(
        streaming, "make_chunked_tokenizer",
        lambda paths, k=1, **kw: _REAL_TOKENIZER(paths, k=k,
                                                 chunk_bytes=400, **kw))


def test_resume_after_pass2_crash(tmp_path, monkeypatch, ref):
    # pins the LEGACY per-batch pass-2 resume specifically: since the
    # radix path became the library default (ISSUE 13 flipped
    # TPU_IR_RADIX_BUCKETS to 16), the legacy path must be requested
    # explicitly (its radix twin lives in test_radix.py)
    corpus, ref_dir = ref
    out = str(tmp_path / "idx")
    monkeypatch.setenv("TPU_IR_RADIX_BUCKETS", "0")

    small_chunks(monkeypatch)
    real_postings = streaming.build_postings_packed_jit
    calls = {"n": 0}

    def crashing(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 3:
            raise RuntimeError("injected pass-2 crash")
        return real_postings(*a, **kw)

    monkeypatch.setattr(streaming, "build_postings_packed_jit", crashing)
    with pytest.raises(RuntimeError, match="injected"):
        build_index_streaming([corpus], out, **BUILD_KW)

    # crash left pass-1 state + at least one complete batch of pair spills
    spill = os.path.join(out, "_spill")
    manifest = os.path.join(spill, PASS1_MANIFEST)
    assert os.path.exists(manifest)
    with np.load(manifest) as z:
        n_batches = int(z["n_batches"])
    assert n_batches >= 4
    done_before = sum(
        streaming._batch_pairs_done(spill, b, BUILD_KW["num_shards"])
        for b in range(n_batches))
    assert 1 <= done_before < n_batches

    # restart: tokenizer must NOT run; only the unfinished batches do
    forbid_tokenizer(monkeypatch)
    calls["n"] = 0
    monkeypatch.setattr(streaming, "build_postings_packed_jit",
                        lambda *a, **kw: (calls.__setitem__(
                            "n", calls["n"] + 1), real_postings(*a, **kw))[1])
    meta = build_index_streaming([corpus], out, **BUILD_KW)
    assert calls["n"] == n_batches - done_before
    assert verify_index(out)["ok"]
    assert_identical(out, ref_dir)
    assert meta.num_pairs == fmt.IndexMetadata.load(ref_dir).num_pairs

    s1, s2 = Scorer.load(ref_dir), Scorer.load(out)
    for q in ["salmon fishing", "quick brown fox", "stock market"]:
        assert s1.search(q) == s2.search(q), q


def test_resume_after_pass3_crash(tmp_path, monkeypatch, ref):
    corpus, ref_dir = ref
    out = str(tmp_path / "idx")

    real_reduce = streaming.reduce_shard_spills
    calls = {"n": 0}

    def crashing(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("injected pass-3 crash")
        return real_reduce(*a, **kw)

    monkeypatch.setattr(streaming, "reduce_shard_spills", crashing)
    with pytest.raises(RuntimeError, match="injected"):
        build_index_streaming([corpus], out, **BUILD_KW)
    assert os.path.exists(os.path.join(out, fmt.part_name(0)))

    # restart: pass 1 AND pass 2 fully skipped, shard 0's part reused
    forbid_tokenizer(monkeypatch)
    monkeypatch.setattr(
        streaming, "build_postings_packed_jit",
        lambda *a, **kw: (_ for _ in ()).throw(
            AssertionError("completed pass-2 batches must not recompute")))
    calls["n"] = 0
    monkeypatch.setattr(streaming, "reduce_shard_spills",
                        lambda *a, **kw: (calls.__setitem__(
                            "n", calls["n"] + 1), real_reduce(*a, **kw))[1])
    build_index_streaming([corpus], out, **BUILD_KW)
    assert calls["n"] == BUILD_KW["num_shards"] - 1
    assert verify_index(out)["ok"]
    assert_identical(out, ref_dir)


def test_stale_state_discarded(tmp_path, monkeypatch, ref):
    """Spills from a DIFFERENT config (other corpus bytes / k / shards)
    and orphaned part files must be wiped, not resumed against."""
    corpus, ref_dir = ref
    other = write_corpus(tmp_path / "other.trec", n_docs=60, skew=5)
    out = str(tmp_path / "idx")

    # leave a crashed build of ANOTHER corpus behind
    real_reduce = streaming.reduce_shard_spills

    def crash_once(*a, **kw):
        raise RuntimeError("injected")

    monkeypatch.setattr(streaming, "reduce_shard_spills", crash_once)
    with pytest.raises(RuntimeError):
        build_index_streaming([other], out, **BUILD_KW)
    monkeypatch.setattr(streaming, "reduce_shard_spills", real_reduce)
    assert os.path.exists(os.path.join(out, "_spill", PASS1_MANIFEST))

    # building the real corpus into the same dir: manifest sig mismatches,
    # so everything is rebuilt from scratch (tokenizer runs) and the stale
    # parts/spills can't leak into the result
    meta = build_index_streaming([corpus], out, **BUILD_KW)
    assert meta.num_docs == 120
    assert verify_index(out)["ok"]
    assert_identical(out, ref_dir)


def test_regenerated_same_size_corpus_not_resumed(tmp_path, monkeypatch):
    """A corpus regenerated with identical byte size (easy with fixed-
    width synthetic docs) must invalidate the resume state: the config
    signature carries mtime, so stale token spills never resume over new
    content (ADVICE r3)."""
    corpus = tmp_path / "corpus.trec"

    def write(word):
        # the word lands in only half the docs (df < N, so idf > 0)
        corpus.write_text("".join(
            f"<DOC>\n<DOCNO> D-{i:04d} </DOCNO>\n<TEXT>\n"
            f"{word if i % 2 else 'forest'} river\n"
            f"</TEXT>\n</DOC>\n" for i in range(40)))

    write("salmon")
    out = str(tmp_path / "idx")
    monkeypatch.setattr(streaming, "reduce_shard_spills",
                        lambda *a, **kw: (_ for _ in ()).throw(
                            RuntimeError("injected")))
    with pytest.raises(RuntimeError):
        build_index_streaming([str(corpus)], out, **BUILD_KW)
    monkeypatch.undo()

    st = corpus.stat()
    write("market")  # same byte size, different content
    assert corpus.stat().st_size == st.st_size
    if corpus.stat().st_mtime_ns == st.st_mtime_ns:
        # coarse-timestamp filesystems: force the mtime tick the rewrite
        # is standing in for, so the test exercises the signature (not
        # the filesystem's clock granularity)
        os.utime(corpus, ns=(st.st_atime_ns, st.st_mtime_ns + 1))
    build_index_streaming([str(corpus)], out, **BUILD_KW)
    s = Scorer.load(out)
    assert s.search("market")
    assert not s.search("salmon")


def test_overwrite_discards_valid_spills(tmp_path, monkeypatch, ref):
    """--overwrite restores build-from-scratch even when a valid resume
    state exists (delete-output-up-front, reference JobConf semantics)."""
    corpus, ref_dir = ref
    out = str(tmp_path / "idx")

    monkeypatch.setattr(streaming, "reduce_shard_spills",
                        lambda *a, **kw: (_ for _ in ()).throw(
                            RuntimeError("injected")))
    with pytest.raises(RuntimeError):
        build_index_streaming([corpus], out, **BUILD_KW)
    monkeypatch.undo()

    tokenized = {"n": 0}
    real_tok = streaming.make_chunked_tokenizer

    def counting(*a, **kw):
        tokenized["n"] += 1
        return real_tok(*a, **kw)

    monkeypatch.setattr(streaming, "make_chunked_tokenizer", counting)
    build_index_streaming([corpus], out, overwrite=True, **BUILD_KW)
    assert tokenized["n"] == 1  # overwrite -> full re-tokenize
    assert_identical(out, ref_dir)
