"""Block-max pruning tests (ISSUE 13): impact-ordered per-block score
bounds in the arena + the branchless block-max top-k kernel family.

THE contract: block-max results are BIT-IDENTICAL (docids, float bits,
tie order) to the exact kernels, whichever in-kernel branch runs — the
masked hot stage computes surviving columns with the same elementwise
weights and the same gemm reduction the full-width stage uses, masked
docs provably cannot reach the top-k, and the overflow fallback IS the
exact stage. The suite pins that across layouts x scorings x k, through
the scorer (scheduled groups, doc_range-restricted workers, coalesced
rung-padded batches), over the serving-cache warm path, and for the
pre-weighted strip cache; plus the artifact half — builder-written
bounds, `migrate-index --add-bounds` backfill, corrupt-bounds
quarantine, and doctor's bound report.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_ir.index import blockmax as bmx
from tpu_ir.index import format as fmt
from tpu_ir.ops.scoring import (
    blockmax_cand_blocks,
    bm25_strip,
    bm25_topk_blockmax,
    bm25_topk_tiered,
    lntf_strip,
    tfidf_topk_blockmax,
    tfidf_topk_tiered,
)
from tpu_ir.search.layout import build_tiered_layout, restrict_tiers

NDOCS = 6000  # > 8 blocks at width 512, wide enough for k=1000


def _zipf_pairs(vocab=2600, ndocs=NDOCS, n_occ=150_000, seed=7):
    rng = np.random.default_rng(seed)
    p = 1.0 / np.arange(1, vocab + 1)
    p /= p.sum()
    t = rng.choice(vocab, n_occ, p=p).astype(np.int64)
    d = rng.integers(1, ndocs + 1, n_occ).astype(np.int64)
    key, tf = np.unique(t * (ndocs + 1) + d, return_counts=True)
    pair_term = (key // (ndocs + 1)).astype(np.int32)
    pair_doc = (key % (ndocs + 1)).astype(np.int32)
    pair_tf = tf.astype(np.int32)
    df = np.bincount(pair_term, minlength=vocab).astype(np.int32)
    return pair_term, pair_doc, pair_tf, df


@pytest.fixture(scope="module")
def layout():
    pair_term, pair_doc, pair_tf, df = _zipf_pairs()
    lay = build_tiered_layout(pair_doc, pair_tf, df, num_docs=NDOCS,
                              hot_budget=16 * (NDOCS + 1))
    doc_len = np.zeros(NDOCS + 1, np.int32)
    np.add.at(doc_len, pair_doc, pair_tf)
    args = (jnp.asarray(lay.hot_rank), lay.hot_device(),
            jnp.asarray(lay.tier_of), jnp.asarray(lay.row_of),
            tuple(jnp.asarray(a) for a in lay.tier_docs),
            tuple(jnp.asarray(a) for a in lay.tier_tfs))
    return (pair_term, pair_doc, pair_tf, df), lay, args, doc_len


def _bound_table(lay, doc_len, scoring, *, k1=0.9, b=0.4):
    """The per-mode [H, nblk] bound table, the scorer's construction."""
    max_tf = np.asarray(lay.hot_blk_max, np.float32)
    if scoring == "tfidf":
        return jnp.asarray(np.where(
            max_tf > 0, 1.0 + np.log(np.maximum(max_tf, 1.0)), 0.0))
    width = lay.blockmax_width
    nblk = max_tf.shape[1]
    dlf = doc_len.astype(np.float32)
    avg = float(dlf.sum()) / NDOCS
    dl_norm = 1.0 - b + b * dlf / max(avg, 1e-9)
    padded = np.full(nblk * width, np.inf, np.float32)
    padded[1: NDOCS + 1] = dl_norm[1: NDOCS + 1]
    dl_min = padded.reshape(nblk, width).min(axis=1)
    dl_min = np.where(np.isfinite(dl_min), dl_min, 0.0)
    sat = max_tf * (k1 + 1.0) / np.maximum(max_tf + k1 * dl_min[None, :],
                                           1e-9)
    return jnp.asarray(np.where(max_tf > 0, sat, 0.0))


def _queries(lay, df, kind, seed=3, rows=6):
    """`rare_hot`: very rare cold terms + one hot term — blocks without
    cold postings are maskable, the pruned branch engages. `hot_only`:
    tau = 0, provably the overflow fallback. `mixed`: everything."""
    rng = np.random.default_rng(seed)
    hot = np.nonzero(lay.hot_rank >= 0)[0]
    rare = np.nonzero((lay.hot_rank < 0) & (df >= 2) & (df <= 8))[0]
    mid = np.nonzero((lay.hot_rank < 0) & (df >= 30) & (df <= 300))[0]
    out = []
    for i in range(rows):
        if kind == "rare_hot":
            out.append([int(rng.choice(hot)), int(rng.choice(rare)),
                        int(rng.choice(rare)), int(rng.choice(rare))])
        elif kind == "hot_only":
            out.append([int(rng.choice(hot)), int(rng.choice(hot)), -1, -1])
        else:
            out.append([int(rng.choice(hot)), int(rng.choice(mid)),
                        int(rng.choice(rare)), -1])
    return np.array(out, np.int32)


def _kernel_pair(args, df, doc_len, scoring, lay):
    n = jnp.int32(NDOCS)
    bound = _bound_table(lay, doc_len, scoring)
    width = lay.blockmax_width
    dl = jnp.asarray(doc_len)

    def exact(q, k):
        if scoring == "bm25":
            return bm25_topk_tiered(jnp.asarray(q), *args, jnp.asarray(df),
                                    dl, n, num_docs=NDOCS, k=k)
        return tfidf_topk_tiered(jnp.asarray(q), *args, jnp.asarray(df),
                                 n, num_docs=NDOCS, k=k)

    def blockmax(q, k, cand_blocks=None):
        cb = cand_blocks or blockmax_cand_blocks(k, NDOCS, width)
        if scoring == "bm25":
            return bm25_topk_blockmax(
                jnp.asarray(q), *args, jnp.asarray(df), dl, n, bound,
                num_docs=NDOCS, width=width, cand_blocks=cb, k=k)
        return tfidf_topk_blockmax(
            jnp.asarray(q), *args, jnp.asarray(df), n, bound,
            num_docs=NDOCS, width=width, cand_blocks=cb, k=k)

    return exact, blockmax


@pytest.mark.parametrize("scoring", ["tfidf", "bm25"])
@pytest.mark.parametrize("k", [10, 100, 1000])
def test_blockmax_bit_identical_to_exact_kernel(layout, scoring, k):
    """THE kernel contract, across the scoring x k matrix and all three
    query regimes (pruned branch, overflow fallback, mixed): identical
    float bits, identical docids, identical tie order."""
    (pt, pd, ptf, df), lay, args, doc_len = layout
    exact, blockmax = _kernel_pair(args, df, doc_len, scoring, lay)
    for kind in ("rare_hot", "hot_only", "mixed"):
        q = _queries(lay, df, kind)
        s_e, d_e = (np.asarray(a) for a in exact(q, k))
        s_b, d_b, _ = (np.asarray(a) for a in blockmax(q, k))
        assert (s_e == s_b).all(), (kind, scoring, k)
        assert (d_e == d_b).all(), (kind, scoring, k)


@pytest.mark.parametrize("scoring", ["tfidf", "bm25"])
def test_pruned_branch_engages_and_masks(scoring, monkeypatch):
    """The masked path must actually RUN (stats fallback flag 0) with a
    real skip fraction — not pass vacuously through the fallback — and
    still match the exact kernel bitwise. Fine blocks (width 128) so
    a handful of very rare cold terms leaves most blocks provably
    cold-free; the query's hot term is the HOTTEST (lowest idf -> a hot
    bound the rare-term threshold dominates); k below the positive cold
    count so tau > 0."""
    monkeypatch.setenv("TPU_IR_BLOCKMAX_WIDTH", "128")
    pair_term, pair_doc, pair_tf, df = _zipf_pairs()
    lay = build_tiered_layout(pair_doc, pair_tf, df, num_docs=NDOCS,
                              hot_budget=16 * (NDOCS + 1))
    assert lay.blockmax_width == 128
    doc_len = np.zeros(NDOCS + 1, np.int32)
    np.add.at(doc_len, pair_doc, pair_tf)
    args = (jnp.asarray(lay.hot_rank), lay.hot_device(),
            jnp.asarray(lay.tier_of), jnp.asarray(lay.row_of),
            tuple(jnp.asarray(a) for a in lay.tier_docs),
            tuple(jnp.asarray(a) for a in lay.tier_tfs))
    exact, blockmax = _kernel_pair(args, df, doc_len, scoring, lay)
    hot = np.nonzero(lay.hot_rank >= 0)[0]
    hottest = int(hot[np.argmax(df[hot])])
    rare = np.nonzero((lay.hot_rank < 0) & (df >= 2) & (df <= 4))[0]
    rng = np.random.default_rng(9)
    engaged = masked_total = 0
    for i in range(8):
        qb = np.array([[hottest, int(rng.choice(rare)),
                        int(rng.choice(rare)), -1]], np.int32)
        s_e, d_e = (np.asarray(a) for a in exact(qb, 5))
        s_b, d_b, stats = (np.asarray(a) for a in blockmax(qb, 5))
        assert (s_e == s_b).all() and (d_e == d_b).all()
        considered, masked, fallback = (int(x) for x in stats)
        assert considered == lay.hot_blk_max.shape[1]
        if not fallback:
            engaged += 1
            masked_total += masked
    assert engaged > 0, "pruned branch never ran — the test corpus no " \
                        "longer produces maskable blocks"
    assert masked_total > 0


def test_overflow_fallback_flagged(layout):
    """Hot-only queries have tau = 0 (no cold partial): every block
    survives, the budget overflows, the stats say fallback — and the
    result is still exact (pinned above); here we pin the FLAG."""
    (pt, pd, ptf, df), lay, args, doc_len = layout
    _, blockmax = _kernel_pair(args, df, doc_len, "bm25", lay)
    q = _queries(lay, df, "hot_only", rows=2)
    _, _, stats = blockmax(q, 10)
    assert int(np.asarray(stats)[2]) == 1


@pytest.mark.parametrize("scoring", ["tfidf", "bm25"])
def test_hot_preweighted_strip_bit_identical(layout, scoring):
    """The device-cached pre-weighted strip (lntf_strip / bm25_strip)
    must be a pure reordering of WHEN the weighting runs: same floats
    from the tiered and block-max kernels either way."""
    (pt, pd, ptf, df), lay, args, doc_len = layout
    hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs = args
    n = jnp.int32(NDOCS)
    dl = jnp.asarray(doc_len)
    if scoring == "bm25":
        ws = bm25_strip(hot_tfs, dl, n)
    else:
        ws = lntf_strip(hot_tfs)
    wargs = (hot_rank, ws, tier_of, row_of, tier_docs, tier_tfs)
    bound = _bound_table(lay, doc_len, scoring)
    width = lay.blockmax_width
    cb = blockmax_cand_blocks(10, NDOCS, width)
    for kind in ("rare_hot", "mixed", "hot_only"):
        q = jnp.asarray(_queries(lay, df, kind))
        if scoring == "bm25":
            raw = bm25_topk_tiered(q, *args, jnp.asarray(df), dl, n,
                                   num_docs=NDOCS, k=10)
            pre = bm25_topk_tiered(q, *wargs, jnp.asarray(df), dl, n,
                                   num_docs=NDOCS, k=10,
                                   hot_preweighted=True)
            braw = bm25_topk_blockmax(q, *args, jnp.asarray(df), dl, n,
                                      bound, num_docs=NDOCS, width=width,
                                      cand_blocks=cb, k=10)
            bpre = bm25_topk_blockmax(q, *wargs, jnp.asarray(df), dl, n,
                                      bound, num_docs=NDOCS, width=width,
                                      cand_blocks=cb, k=10,
                                      hot_preweighted=True)
        else:
            raw = tfidf_topk_tiered(q, *args, jnp.asarray(df), n,
                                    num_docs=NDOCS, k=10)
            pre = tfidf_topk_tiered(q, *wargs, jnp.asarray(df), n,
                                    num_docs=NDOCS, k=10,
                                    hot_preweighted=True)
            braw = tfidf_topk_blockmax(q, *args, jnp.asarray(df), n,
                                       bound, num_docs=NDOCS, width=width,
                                       cand_blocks=cb, k=10)
            bpre = tfidf_topk_blockmax(q, *wargs, jnp.asarray(df), n,
                                       bound, num_docs=NDOCS, width=width,
                                       cand_blocks=cb, k=10,
                                       hot_preweighted=True)
        for a, b in zip(raw, pre):
            assert (np.asarray(a) == np.asarray(b)).all(), (scoring, kind)
        for a, b in zip(braw[:2], bpre[:2]):
            assert (np.asarray(a) == np.asarray(b)).all(), (scoring, kind)


def test_restricted_bounds_stay_sound(layout):
    """restrict_tiers composes with bounds: blocks wholly outside the
    doc range drop to 0, every other bound still dominates the
    restricted strip's actual block maxima (sound overestimates)."""
    (pt, pd, ptf, df), lay, args, doc_len = layout
    lo, hi = NDOCS // 3, 2 * NDOCS // 3
    r = restrict_tiers(lay, lo, hi)
    w = r.blockmax_width
    actual = bmx.coo_block_max(r.hot_rows, r.hot_docs,
                               np.where((np.asarray(r.hot_docs) >= lo)
                                        & (np.asarray(r.hot_docs) <= hi),
                                        r.hot_vals, 0),
                               num_rows=r.num_hot, num_docs=NDOCS, width=w)
    assert (np.asarray(r.hot_blk_max) >= actual).all()
    nblk = r.hot_blk_max.shape[1]
    starts = np.arange(nblk) * w
    outside = (starts + w - 1 < lo) | (starts > hi)
    assert (np.asarray(r.hot_blk_max)[:, outside] == 0).all()


def test_cand_blocks_budget():
    # covers 2k candidate docs, floors at 4, env override wins
    assert blockmax_cand_blocks(10, 100_000, 512) >= 4
    nblk = -(-100_001 // 512)
    assert blockmax_cand_blocks(10, 100_000, 512) >= nblk // 4
    assert blockmax_cand_blocks(5000, 100_000, 512) * 512 >= 10_000
    os.environ["TPU_IR_BLOCKMAX_BLOCKS"] = "7"
    try:
        assert blockmax_cand_blocks(10, 100_000, 512) == 7
    finally:
        del os.environ["TPU_IR_BLOCKMAX_BLOCKS"]


# -- end-to-end through the Scorer ------------------------------------------


def _write_corpus(path, ndocs=4000, seed=5):
    import bench

    bench.make_corpus(path, seed=seed, n_docs=ndocs)


@pytest.fixture(scope="module")
def index_dir(tmp_path_factory):
    from tpu_ir.index import build_index

    tmp = tmp_path_factory.mktemp("bmxidx")
    corpus = os.path.join(tmp, "c.trec")
    _write_corpus(corpus)
    idx = os.path.join(tmp, "index")
    build_index([corpus], idx, k=1, chargram_ks=[], num_shards=3,
                compute_chargrams=False)
    return idx


def _scorer_queries(s, seed=0, rows=24, pools_from=None):
    rng = np.random.default_rng(seed)
    src = pools_from if pools_from is not None else s
    df = np.asarray(src.df)
    hr = np.asarray(src.hot_rank)
    hot = np.nonzero(hr >= 0)[0]
    rare = np.nonzero((hr < 0) & (df >= 2) & (df <= 10))[0]
    mid = np.nonzero((hr < 0) & (df >= 20) & (df <= 400))[0]
    rows_out = []
    for i in range(rows):
        pools = ([hot, rare, rare], [hot, mid, rare], [mid, mid, rare],
                 [hot, hot, hot])[i % 4]
        rows_out.append([int(rng.choice(p)) for p in pools] + [-1])
    return np.array(rows_out, np.int32)


def _on_off(s, fn):
    on = fn()
    os.environ["TPU_IR_BLOCKMAX"] = "0"
    os.environ["TPU_IR_BLOCKMAX_STRIP_CACHE"] = "0"
    try:
        off = fn()
    finally:
        del os.environ["TPU_IR_BLOCKMAX"]
        del os.environ["TPU_IR_BLOCKMAX_STRIP_CACHE"]
    return on, off


@pytest.mark.parametrize("scoring", ["tfidf", "bm25"])
@pytest.mark.parametrize("k", [10, 100, 1000])
def test_scorer_parity_tiered(index_dir, scoring, k, monkeypatch):
    """Scorer-level block-max on == off, bit-identical, through the
    scheduled-group dispatch (mixed hot/hot-free batches), at every k —
    the engagement knob can never change a result."""
    from tpu_ir.search import Scorer

    s = Scorer.load(index_dir, layout="sparse")
    q = _scorer_queries(s)
    (s_on, d_on), (s_off, d_off) = _on_off(
        s, lambda: s.topk(q, k=k, scoring=scoring))
    assert (np.asarray(s_on) == np.asarray(s_off)).all()
    assert (np.asarray(d_on) == np.asarray(d_off)).all()


def test_scorer_parity_dense_layout(index_dir):
    """Across layouts: on the dense layout block-max is a documented
    no-op — the knob must not change a single bit there either."""
    from tpu_ir.search import Scorer

    s = Scorer.load(index_dir, layout="dense")
    assert s._blockmax_plan(10, "bm25") is None
    pools = Scorer.load(index_dir, layout="sparse")
    q = _scorer_queries(s, pools_from=pools)
    (s_on, d_on), (s_off, d_off) = _on_off(
        s, lambda: s.topk(q, k=10, scoring="bm25"))
    assert (np.asarray(s_on) == np.asarray(s_off)).all()
    assert (np.asarray(d_on) == np.asarray(d_off)).all()


def test_scorer_parity_sharded_layout(index_dir):
    """Sharded layout (single-device mesh here): same no-op contract."""
    from tpu_ir.search import Scorer

    s = Scorer.load(index_dir, layout="sharded")
    assert s._blockmax_plan(10, "bm25") is None
    pools = Scorer.load(index_dir, layout="sparse")
    q = _scorer_queries(s, pools_from=pools)
    (s_on, d_on), (s_off, d_off) = _on_off(
        s, lambda: s.topk(q, k=10, scoring="bm25"))
    assert (np.asarray(s_on) == np.asarray(s_off)).all()
    assert (np.asarray(d_on) == np.asarray(d_off)).all()


def test_scorer_parity_hot_only_and_doc_range(index_dir):
    """hot_only (ladder degradation) and doc_range (scatter-gather
    worker restriction) both compose: on == off bitwise."""
    from tpu_ir.search import Scorer

    s = Scorer.load(index_dir, layout="sparse")
    q = _scorer_queries(s)
    (a_on, b_on), (a_off, b_off) = _on_off(
        s, lambda: s.topk(q, k=10, scoring="bm25", hot_only=True))
    assert (np.asarray(a_on) == np.asarray(a_off)).all()
    assert (np.asarray(b_on) == np.asarray(b_off)).all()

    d = s.meta.num_docs
    w = Scorer.load(index_dir, layout="sparse",
                    doc_range=(d // 4, 3 * d // 4))
    (a_on, b_on), (a_off, b_off) = _on_off(
        w, lambda: w.topk(q, k=100, scoring="bm25"))
    assert (np.asarray(a_on) == np.asarray(a_off)).all()
    assert (np.asarray(b_on) == np.asarray(b_off)).all()


def test_scorer_parity_coalesced_rungs(index_dir):
    """The coalesced serving shape (rung-padded uniform dispatch): the
    block-max program rides the same rung ladder; on == off bitwise,
    and coalesced == plain for the same queries."""
    from tpu_ir.search import Scorer

    s = Scorer.load(index_dir, layout="sparse")
    q = _scorer_queries(s, rows=6)
    rungs = (1, 4, 16)

    def run():
        sc, dc, deg = s.topk_tagged(q, k=10, scoring="bm25",
                                    uniform=rungs)
        assert not deg
        return sc, dc

    (s_on, d_on), (s_off, d_off) = _on_off(s, run)
    assert (np.asarray(s_on) == np.asarray(s_off)).all()
    assert (np.asarray(d_on) == np.asarray(d_off)).all()
    # (coalesced vs non-uniform topk() is NOT asserted bitwise: the two
    # pad to different batch shapes, whose gemm rounding may differ —
    # the ladder pins coalesced == solo through equal rung shapes,
    # test_batching's contract; here the knob-parity is the claim)


def test_scorer_engagement_counters(index_dir):
    """The registry ledger: block-max dispatches land raw counters
    (considered/masked + saved-or-fallback), and the scheduled-skip
    plan lands the prune.* raw terms."""
    from tpu_ir.obs import get_registry
    from tpu_ir.search import Scorer

    s = Scorer.load(index_dir, layout="sparse")
    q = _scorer_queries(s)
    get_registry().snapshot(reset=True)
    s.topk(q, k=10, scoring="bm25")
    c = get_registry().snapshot()["counters"]
    assert c["prune.queries"] == len(q)
    assert c["prune.blocks_total"] >= 1
    assert c["blockmax.blocks_considered"] > 0
    assert (c["blockmax.saved_dispatches"]
            + c["blockmax.fallback_dispatches"]) >= 1


def test_explain_pins_blockmax_scores(index_dir):
    """The PR 8 explain harness closes the loop: the telescoped partial
    sums must equal the block-max-served score bit-exactly (the explain
    gather traces the same cold-first accumulation the block-max kernel
    realizes)."""
    from tpu_ir.search import Scorer

    s = Scorer.load(index_dir, layout="sparse")
    vocab_terms = s.vocab.terms
    hr = np.asarray(s.hot_rank)
    df = np.asarray(s.df)
    hot = np.nonzero(hr >= 0)[0]
    rare = np.nonzero((hr < 0) & (df >= 2) & (df <= 10))[0]
    text = f"{vocab_terms[hot[0]]} {vocab_terms[rare[0]]} " \
           f"{vocab_terms[rare[1]]}"
    res = s.search_batch([text], k=5, scoring="bm25", explain_k=1,
                         return_docids=True)[0]
    if not res:
        pytest.skip("query matched nothing")
    e = res.explain[0]
    assert e["contribution_sum"] == e["score"] == res[0][1]


# -- artifact half ----------------------------------------------------------


def test_bounds_artifact_written_and_consistent(index_dir):
    """Every builder finalize writes blockmax.arena (the
    save_with_checksums hook); its stored maxima equal what the layout
    recomputes from the postings, and the checksum covers it."""
    from tpu_ir.search import Scorer

    path = os.path.join(index_dir, bmx.BLOCKMAX_ARENA)
    assert os.path.exists(path)
    meta = fmt.IndexMetadata.load(index_dir)
    assert bmx.BLOCKMAX_ARENA in meta.checksums
    tids, max_tf, width = bmx.load_block_bounds(index_dir, meta)
    s = Scorer.load(index_dir, layout="sparse")
    hr = np.asarray(s.hot_rank)
    assert np.array_equal(np.sort(np.nonzero(hr >= 0)[0]), tids)
    # stored rows, reordered to strip rank order == the served table
    rank = hr[tids]
    served = np.asarray(s._hot_blk_max)
    assert np.array_equal(served[rank], max_tf)
    assert width == s._blockmax_width


def test_migrate_add_bounds_roundtrip(index_dir, tmp_path):
    """Backfill: strip the bounds from a copy (a pre-13 index), verify
    still passes, `migrate-index --add-bounds` restores byte-identical
    bounds, is idempotent, and the index serves identically."""
    import shutil

    from tpu_ir.index.migrate import migrate_index
    from tpu_ir.index.verify import verify_index
    from tpu_ir.search import Scorer

    idx = str(tmp_path / "copy")
    shutil.copytree(index_dir, idx)
    want = open(os.path.join(index_dir, bmx.BLOCKMAX_ARENA), "rb").read()
    os.remove(os.path.join(idx, bmx.BLOCKMAX_ARENA))
    shutil.rmtree(os.path.join(idx, "serving-tiered"), ignore_errors=True)
    meta = fmt.IndexMetadata.load(idx)
    meta.save_with_checksums(idx, block_bounds=False)
    verify_index(idx)  # a pre-bounds index stays verify-clean

    out = migrate_index(idx, add_bounds=True)
    assert out["ok"] and out["add_bounds"]
    got = open(os.path.join(idx, bmx.BLOCKMAX_ARENA), "rb").read()
    assert got == want  # deterministic backfill == builder output
    verify_index(idx)
    out2 = migrate_index(idx, add_bounds=True)  # idempotent
    assert out2["ok"]
    assert open(os.path.join(idx, bmx.BLOCKMAX_ARENA), "rb").read() == want

    s0 = Scorer.load(index_dir, layout="sparse")
    s1 = Scorer.load(idx, layout="sparse")
    q = _scorer_queries(s0)
    a = s0.topk(q, k=10, scoring="bm25")
    b = s1.topk(q, k=10, scoring="bm25")
    assert (np.asarray(a[0]) == np.asarray(b[0])).all()
    assert (np.asarray(a[1]) == np.asarray(b[1])).all()


def test_corrupt_bounds_quarantined_and_served(index_dir, tmp_path):
    """PR 1 discipline for the bounds artifact: flipped bytes are
    quarantined on load (bounds are derived data — the scorer recomputes
    and serves bit-identically), while `tpu-ir verify` still fails the
    dir loudly."""
    import shutil

    from tpu_ir import faults
    from tpu_ir.index.verify import verify_index
    from tpu_ir.search import Scorer

    idx = str(tmp_path / "corrupt")
    shutil.copytree(index_dir, idx)
    shutil.rmtree(os.path.join(idx, "serving-tiered"), ignore_errors=True)
    path = os.path.join(idx, bmx.BLOCKMAX_ARENA)
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(path, "wb").write(bytes(raw))

    with pytest.raises(faults.IntegrityError):
        verify_index(idx)

    s = Scorer.load(idx, layout="sparse")  # quarantines, then recomputes
    assert not os.path.exists(path)
    qdir = os.path.join(idx, ".quarantine")
    assert any(bmx.BLOCKMAX_ARENA in n for n in os.listdir(qdir))
    assert s._hot_blk_max is not None
    s0 = Scorer.load(index_dir, layout="sparse")
    q = _scorer_queries(s0)
    a = s0.topk(q, k=10, scoring="bm25")
    b = s.topk(q, k=10, scoring="bm25")
    assert (np.asarray(a[0]) == np.asarray(b[0])).all()
    assert (np.asarray(a[1]) == np.asarray(b[1])).all()


def test_serving_cache_v6_carries_bounds(index_dir):
    """The warm path: a serving-cache hit yields the same bounds (and
    the same results) with zero postings IO."""
    from tpu_ir.search import Scorer

    s_cold = Scorer.load(index_dir, layout="sparse")
    s_warm = Scorer.load(index_dir, layout="sparse")
    assert s_warm._pairs_cols is None  # cache fast path engaged
    assert s_warm._hot_blk_max is not None
    assert np.array_equal(np.asarray(s_warm._hot_blk_max),
                          np.asarray(s_cold._hot_blk_max))
    q = _scorer_queries(s_cold)
    a = s_cold.topk(q, k=100, scoring="bm25")
    b = s_warm.topk(q, k=100, scoring="bm25")
    assert (np.asarray(a[0]) == np.asarray(b[0])).all()
    assert (np.asarray(a[1]) == np.asarray(b[1])).all()


def test_doctor_reports_bounds(index_dir):
    from tpu_ir.index.doctor import doctor_report

    rep = doctor_report(index_dir)
    bb = rep["block_bounds"]
    assert bb["present"] and bb["ok"] and not bb["stale"]
    assert bb["bounds_exact"]
    assert 0.0 < bb["block_occupancy"] <= 1.0


def test_cli_migrate_add_bounds_smoke(index_dir, tmp_path):
    import shutil

    idx = str(tmp_path / "cli")
    shutil.copytree(index_dir, idx)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, "-m", "tpu_ir.cli", "migrate-index", idx,
         "--add-bounds"],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    out = json.loads(r.stdout)
    assert out["ok"] and out["add_bounds"] and out["terms"] >= 0
