"""End-to-end STREAMING multi-host index build: 2 processes x 2 CPU devices
build one index into a shared directory through the chunked scanner +
per-batch SPMD shuffle (batch_docs=2 forces several lockstep steps per
process, proving no process ever holds its slice in memory); artifacts must
be byte-identical to the single-process streaming build at the same shard
count and produce identical search results."""

import os
import socket
import subprocess
import sys

import pytest

DOCS = {
    "A-1": "alpha bravo charlie alpha",
    "A-2": "delta echo foxtrot bravo",
    "B-1": "alpha golf hotel india",
    "B-2": "charlie juliet kilo lima bravo",
    "C-1": "echo mike november oscar",
    "C-2": "papa quebec romeo alpha charlie",
}

WORKER = r"""
import os, sys, json
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
import jax._src.xla_bridge as xb
for n in list(xb._backend_factories):
    if n != "cpu":
        xb._backend_factories.pop(n, None)

coordinator, pid, corpus_dir, index_dir = (
    sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4])
from tpu_ir.parallel.multihost import init_distributed, build_index_multihost

init_distributed(coordinator, num_processes=2, process_id=pid)
from tpu_ir import obs
from tpu_ir.obs import aggregate

# a process-distinct marker so the cluster-total assertion cannot pass
# vacuously on all-zero counters
obs.get_registry().incr("test.proc_marker", 100 + pid)
meta = build_index_multihost([corpus_dir], index_dir, k=1,
                             compute_chargrams=False, batch_docs=2,
                             positions=True, store=True)
# cluster telemetry: my local snapshot, then the LIVE allgathered merge
# (a collective — both processes call it together after their builds)
local = aggregate.local_snapshot()
cluster = aggregate.gather_cluster()
telemetry_out = os.environ["TPU_IR_TEST_TELEMETRY_OUT"]
with open(os.path.join(telemetry_out, f"local-{pid}.json"), "w") as f:
    json.dump(local, f)
with open(os.path.join(telemetry_out, f"cluster-{pid}.json"), "w") as f:
    json.dump(cluster, f)
print(json.dumps({"pid": pid, "num_docs": meta.num_docs,
                  "num_shards": meta.num_shards,
                  "vocab_size": meta.vocab_size,
                  "has_positions": meta.has_positions}))
"""


def test_multihost_build(tmp_path):
    corpus_dir = tmp_path / "corpus"
    corpus_dir.mkdir()
    # several files so the round-robin slice gives each process some
    for name in ["A", "B", "C"]:
        (corpus_dir / f"{name}.trec").write_text("".join(
            f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{t}\n</TEXT>\n</DOC>\n"
            for d, t in DOCS.items() if d.startswith(name)))

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    index_dir = str(tmp_path / "mh_index")

    spool_dir = tmp_path / "spool"
    telemetry_out = tmp_path / "telemetry"
    spool_dir.mkdir()
    telemetry_out.mkdir()
    env = {**os.environ, "PYTHONPATH": os.getcwd(),
           # each worker spools its final registry snapshot here (the
           # post-mortem aggregation path) and dumps its local + live
           # allgathered cluster views into telemetry_out
           "TPU_IR_TELEMETRY_DIR": str(spool_dir),
           "TPU_IR_TEST_TELEMETRY_OUT": str(telemetry_out)}
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), f"127.0.0.1:{port}", str(pid),
             str(corpus_dir), index_dir],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            cwd=os.getcwd(), text=True)
        for pid in range(2)
    ]
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, f"worker failed:\n{err[-4000:]}"

    # validate in THIS (single) process
    import numpy as np

    from tpu_ir.index import format as fmt
    from tpu_ir.index.streaming import build_index_streaming
    from tpu_ir.index.verify import verify_index
    from tpu_ir.search import Scorer

    summary = verify_index(index_dir)
    assert summary["ok"] and summary["num_docs"] == len(DOCS)
    assert fmt.IndexMetadata.load(index_dir).num_shards == 4
    # local spills cleaned up from the shared dir
    assert not [n for n in os.listdir(index_dir) if n.startswith("_spill")]

    # byte-identical to the single-process streaming build at 4 shards
    # (positions included: each process only held ITS docs' token
    # streams, so identical position files prove the shared-spill
    # re-alignment)
    import filecmp

    from tpu_ir.index.positions import positions_name

    ref_dir = str(tmp_path / "ref_index")
    build_index_streaming([str(corpus_dir)], ref_dir, k=1, num_shards=4,
                          batch_docs=2, compute_chargrams=False,
                          positions=True)
    for s in range(4):
        z1, z2 = fmt.load_shard(ref_dir, s), fmt.load_shard(index_dir, s)
        for key in ["term_ids", "indptr", "pair_doc", "pair_tf", "df"]:
            np.testing.assert_array_equal(z1[key], z2[key],
                                          err_msg=f"{s}/{key}")
        assert filecmp.cmp(os.path.join(ref_dir, positions_name(s)),
                           os.path.join(index_dir, positions_name(s)),
                           shallow=False), s
    for name in [fmt.DICTIONARY, fmt.DOCNOS, fmt.VOCAB]:
        assert (open(os.path.join(ref_dir, name), "rb").read()
                == open(os.path.join(index_dir, name), "rb").read()), name
    np.testing.assert_array_equal(
        np.load(os.path.join(ref_dir, fmt.DOCLEN)),
        np.load(os.path.join(index_dir, fmt.DOCLEN)))

    s_mh = Scorer.load(index_dir)
    s_ref = Scorer.load(ref_dir)
    for q in ["alpha", "charlie bravo", "echo", "zulu"]:
        assert s_mh.search(q) == s_ref.search(q), q

    # docstore folded into the multi-host pass 1 (store=True above):
    # process 0 assembled it from the shared text spills; every doc's
    # stored content must match, keyed by docno through the mapping
    from tpu_ir.index.docstore import DocStore, available

    assert available(index_dir)
    store = DocStore(index_dir)
    for docid, text in DOCS.items():
        content = store.get(s_mh.mapping.get_docno(docid))
        assert text in content and docid in content

    # --- cluster telemetry (ISSUE 4 acceptance): the allgathered
    # cluster snapshot's counter totals equal the sum of the two
    # per-process snapshots, both processes hold the same merged view,
    # and the file-spool post-mortem merge agrees with the live one ---
    import json

    from tpu_ir.obs import aggregate

    locals_ = [json.load(open(telemetry_out / f"local-{p}.json"))
               for p in range(2)]
    clusters = [json.load(open(telemetry_out / f"cluster-{p}.json"))
                for p in range(2)]
    assert clusters[0]["counters"] == clusters[1]["counters"]
    assert clusters[0]["histograms"] == clusters[1]["histograms"]
    cluster = clusters[0]
    assert cluster["processes"] == 2
    for key in {k for l in locals_ for k in l["counters"]}:
        assert cluster["counters"][key] == sum(
            l["counters"].get(key, 0) for l in locals_), key
    assert cluster["counters"]["test.proc_marker"] == 100 + 101
    # the build phases really were observed on both processes and the
    # cluster histogram counts are the per-process sums
    for name in ("build.spill", "build.spill_reduce"):
        want = sum(sum(l["histograms"][name]["counts"]) for l in locals_)
        assert want > 0
        assert cluster["histograms"][name]["count"] == want, name
    # post-mortem path: each worker spooled its snapshot on build exit
    spooled = aggregate.read_spool(str(spool_dir))
    assert len(spooled) == 2
    merged = aggregate.merge_snapshots(spooled)
    assert merged["counters"] == cluster["counters"]
    assert merged["histograms"] == cluster["histograms"]
