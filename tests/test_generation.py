"""Zero-downtime generation swap through the serving tier (ISSUE 12).

The serving half of the live-index subsystem: a frontend publishes a
new generation's scorer without dropping (or tearing) in-flight
requests, every response is tagged with the exact corpus snapshot that
answered it, shard workers reload over /rpc/reload, the router merges
only single-generation responses across the rolling window — and THE
acceptance: the distributed chaos soak's upgrade-mid-soak schedule
holds conservation with a bounded mixed-generation window.
"""

import json
import random
import threading

import pytest

from tpu_ir.index.ingest import IngestWriter
from tpu_ir.index.segments import LiveIndex
from tpu_ir.search.scorer import Scorer
from tpu_ir.serving import (
    Router,
    RouterConfig,
    ServingConfig,
    ServingFrontend,
    rolling_swap,
    run_distributed_soak,
    serve_worker,
    swap_microbench,
)

WORDS = ("salmon fishing river bears honey quick brown fox lazy dog "
         "market investor asset bond stock season rain forest".split())


def _text(rng) -> str:
    return " ".join(rng.choice(WORDS) for _ in range(rng.randint(3, 7)))


@pytest.fixture(scope="module")
def live_dir(tmp_path_factory):
    """A live index with two compacted generations: gen A (40 docs)
    and gen B (A + 8 updates/adds) — the swap fixture."""
    tmp = tmp_path_factory.mktemp("gen")
    live = str(tmp / "live")
    LiveIndex.create(live, num_shards=2)
    rng = random.Random(0)
    with IngestWriter(live, auto_merge=False) as w:
        for i in range(40):
            w.add(f"D-{i:03d}", _text(rng))
        w.compact_all(note="gen A")
    gen_a = LiveIndex.open(live).current_gen()
    with IngestWriter(live, auto_merge=False) as w:
        for i in range(4):
            w.update(f"D-{i:03d}", _text(rng))      # replace
        for i in range(4):
            w.update(f"N-{i:03d}", _text(rng))      # new docs
        w.compact_all(note="gen B")
    gen_b = LiveIndex.open(live).current_gen()
    assert gen_b > gen_a
    return live, gen_a, gen_b


QUERIES = ["salmon fishing", "bears honey market", "quick fox",
           "rain forest investor", "asset bond stock"]


def test_scorer_load_and_reload_generation(live_dir):
    live, gen_a, gen_b = live_dir
    a = Scorer.load_generation(live, gen_a, layout="sparse")
    assert a.generation == gen_a
    assert a.meta.num_docs == 40
    b = a.reload_generation()          # current = gen B
    assert b.generation == gen_b
    assert b.meta.num_docs == 44
    # the old scorer is untouched and still answers (in-flight safety)
    assert a.generation == gen_a
    assert len(a.search("salmon", k=3, scoring="bm25")) > 0
    # plain (non-live) scorers refuse: there is nothing to follow
    with pytest.raises(ValueError):
        b2 = Scorer.load(live + "/segments/" + LiveIndex.open(
            live).manifest(gen_b)["segments"][0])
        b2.reload_generation()


def test_frontend_swap_is_atomic_under_traffic(live_dir):
    """Concurrent searchers across a reload_generation: nothing drops,
    nothing tears — every response bit-matches the serial reference of
    the generation it is TAGGED with."""
    live, gen_a, gen_b = live_dir
    ref = {}
    for g in (gen_a, gen_b):
        sc = Scorer.load_generation(live, g, layout="sparse")
        ref[g] = {q: list(sc.search_batch([q], k=5,
                                          scoring="bm25")[0])
                  for q in QUERIES}
    frontend = ServingFrontend(
        Scorer.load_generation(live, gen_a, layout="sparse"),
        ServingConfig(max_concurrency=4, max_queue=64))
    stop = threading.Event()
    outcomes: list = []
    lock = threading.Lock()

    def client(ci: int) -> None:
        rng = random.Random(ci)
        while not stop.is_set():
            q = QUERIES[rng.randrange(len(QUERIES))]
            res = frontend.search(q, k=5, scoring="bm25")
            with lock:
                outcomes.append((q, res.generation, list(res)))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    try:
        # let gen-A traffic accumulate, swap mid-stream, keep serving
        while True:
            with lock:
                if len(outcomes) >= 20:
                    break
        frontend.reload_generation(generation=gen_b)
        baseline = len(outcomes)
        while True:
            with lock:
                if len(outcomes) >= baseline + 20:
                    break
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert frontend.scorer.generation == gen_b
    assert frontend.stats()["generation_swap"] == 1
    gens = {g for _, g, _ in outcomes}
    assert gens == {gen_a, gen_b}, gens
    for q, g, hits in outcomes:
        assert hits == ref[g][q], (
            f"torn response: {q!r} tagged gen {g} diverges from that "
            "generation's serial reference")


def test_worker_reload_and_router_mixed_generation(live_dir):
    """In-process shard workers: reload ONE shard to gen B — the
    router must answer from exactly one generation per response
    (winner by shard count, ties to newest, losers tagged missing) —
    then reload the other and converge."""
    live, gen_a, gen_b = live_dir
    workers = [serve_worker(live, s, 2, index_generation=gen_a,
                            warm=False) for s in range(2)]
    servers = [w[0] for w in workers]
    grid = [[f"127.0.0.1:{srv.port}"] for srv in servers]
    try:
        with Router(live, grid,
                    RouterConfig(deadline_ms=10000.0,
                                 health_ttl_s=0.0)) as router:
            r0 = router.search("salmon fishing", k=5, scoring="bm25")
            assert r0.generation == gen_a and not r0.partial
            # roll shard 0 only -> a mixed window: 1 shard per
            # generation, tie broken to the NEWEST; the gen-A shard is
            # discarded and tagged missing (partial)
            out = rolling_swap([grid[0]], generation=gen_b)
            assert out["generation"] == gen_b and not out["failed"]
            r1 = router.search("salmon fishing", k=5, scoring="bm25")
            assert r1.generation == gen_b
            assert r1.partial and 1 in r1.missing_shards
            from tpu_ir import obs

            assert obs.get_registry().get(
                "router.mixed_generation") >= 1
            # roll the rest -> converged, full, gen B everywhere
            out = rolling_swap([grid[1]], generation=gen_b)
            assert not out["failed"]
            r2 = router.search("salmon fishing", k=5, scoring="bm25")
            assert r2.generation == gen_b and not r2.partial
            # the docids are mapped through gen B's docno space
            ref_b = Scorer.load_generation(live, gen_b, layout="sparse")
            assert list(r2) == list(ref_b.search_batch(
                ["salmon fishing"], k=5, scoring="bm25")[0])
            # /healthz names the worker's index generation
            h = router.health_summary()
            gens = {rep["worker"]["index_generation"]
                    for sh in h["shards"] for rep in sh["replicas"]
                    if rep.get("worker")}
            assert gens == {gen_b}
    finally:
        for srv in servers:
            srv.stop()


def test_swap_microbench_reports(tmp_path):
    report = swap_microbench(str(tmp_path / "bench-live"),
                             base_docs=12, delta_docs=4,
                             probe_s=0.6, num_shards=2)
    assert report["generation_b"] > report["generation_a"]
    assert report["probes"] > 0
    assert report["swap_gap_ms"] >= 0
    assert report["swap_staleness_ms"] >= 0
    assert report["generations_seen"][-1] == report["generation_b"]


def test_cli_ingest_swap_bench(tmp_path, capsys, monkeypatch):
    from tpu_ir.cli import main

    # keep the bench row out of the repo's checked-in history
    monkeypatch.chdir(tmp_path)
    rc = main(["ingest", str(tmp_path / "bench-live"), "--swap-bench"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "swap_gap_ms" in out and out["history_row"][
        "config"] == "ingest_swap"


# ---------------------------------------------------------------------------
# THE acceptance: upgrade-mid-soak through the distributed tier
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_upgrade_mid_soak(tmp_path):
    """Rolling generation handoff under live routed traffic (real
    subprocess workers): conservation holds, zero errors, every
    response is tagged with exactly one known generation and
    bit-matches THAT generation's serial reference, the mixed window
    is bounded by the in-flight wave, and the fleet converges on
    generation B (recovery probes all full, all gen B)."""
    live = str(tmp_path / "live")
    LiveIndex.create(live, num_shards=2)
    rng = random.Random(3)
    with IngestWriter(live, auto_merge=False) as w:
        for i in range(60):
            w.add(f"D-{i:03d}", _text(rng))
        w.compact_all(note="base")

    report = run_distributed_soak(
        live, shards=2, replicas=1, threads=6, queries=90, seed=1,
        chaos=False, upgrade_at=0.25, upgrade_docs=6,
        worker_deadline_s=3.0,
        router_config=RouterConfig(deadline_ms=8000.0, max_queue=128),
        rundir=str(tmp_path / "run"),
        flight_dir=str(tmp_path / "flight"),
        recovery_timeout_s=120.0)
    up = report["upgrade"]
    gen_a, gen_b = up["generation_a"], up["generation_b"]
    # conservation + structure
    assert report["served"] + report["shed"] == report["submitted"]
    assert report["errors"] == 0, report["error_samples"]
    assert report["deadlocked"] == 0
    # the swap actually ran, confirmed on every replica
    assert up["swap"] is not None and not up["swap"]["failed"]
    assert len(up["swap"]["swapped"]) == 2
    # every response named a known generation; both sides of the swap
    # carried traffic; nothing bit-diverged from its own reference
    assert report["unknown_generation"] == 0
    gens = {int(g) for g in report["generations_served"]}
    assert gens <= {gen_a, gen_b}
    assert report["generations_served"].get(str(gen_b), 0) > 0
    assert report["full_mismatches"] == 0
    assert report["partial_mismatches"] == 0
    # the mixed-generation window is BOUNDED: after the roll confirmed,
    # only the in-flight wave may still answer from gen A
    assert up["late_old_generation"] == 0
    # converged: the post-soak serial probes are all full AND gen B
    assert report["recovery_full"] == report["recovery_probes"]
