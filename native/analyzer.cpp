// tpu-ir native analysis pipeline: tag tokenizer -> stopword filter -> Porter2.
//
// Exact behavioral mirror of tpu_ir/analysis (tag_tokenizer.py, porter2.py,
// stopwords.py) for ASCII documents; the Python side routes any document
// containing a byte >= 0x80 to the pure-Python analyzer instead, so this file
// never needs Unicode case folding. Parity is enforced by fuzz tests
// (tests/test_native.py) comparing this against the Python implementation.
//
// Role in the framework: the reference engine's hot loops #2/#3 (per-char
// TagTokenizer scan and Snowball stemming, SURVEY.md §3.1) live host-side;
// this is their native equivalent so host tokenization keeps pace with the
// TPU device ops.
//
// C API (ctypes):
//   ir_set_stopwords(blob, len)      '\n'-separated stopword list
//   ir_analyze(text, len, out, cap)  tokens '\n'-joined; returns bytes
//                                    written, or -(needed) if cap too small

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

// ---------------------------------------------------------------- tokenizer

bool split_table[256];
bool split_table_init = false;

void init_splits() {
  if (split_table_init) return;
  memset(split_table, 0, sizeof(split_table));
  const char *extras = ";\"&/:!#?$%()@^*+-,=><[]{}|`~_";
  for (const char *p = extras; *p; ++p) split_table[(uint8_t)*p] = true;
  for (int c = 0; c <= 32; ++c) split_table[c] = true;
  split_table_init = true;
}

inline bool is_lower(char c) { return c >= 'a' && c <= 'z'; }
inline bool is_upper(char c) { return c >= 'A' && c <= 'Z'; }
inline bool is_digit(char c) { return c >= '0' && c <= '9'; }

// token status per reference checkTokenStatus semantics
enum Status { CLEAN = 0, SIMPLE = 1, COMPLEX = 2, ACRONYM = 3 };

Status classify(const std::string &tok) {
  Status st = CLEAN;
  for (char c : tok) {
    if (is_lower(c) || is_digit(c)) continue;
    if (c == '.') return ACRONYM;
    if ((is_upper(c) || c == '\'') && st == CLEAN) st = SIMPLE;
    else if (!(is_upper(c) || c == '\'')) st = COMPLEX;
  }
  return st;
}

std::string simple_fix(const std::string &tok) {
  std::string out;
  out.reserve(tok.size());
  for (char c : tok) {
    if (is_upper(c)) out.push_back(c + 32);
    else if (c == '\'') continue;
    else out.push_back(c);
  }
  return out;
}
// complex fix == simple fix for ASCII (no further lowercasing possible)

struct Tokenizer {
  const char *text;
  int32_t n;
  std::vector<std::string> tokens;
  std::string ignore_until;  // empty = not ignoring
  // optional sink: when set, add() forwards each final token instead of
  // storing it (corpus mode interns directly — no per-doc string vector)
  void (*sink)(void *, const std::string &) = nullptr;
  void *sink_ctx = nullptr;

  void add(const std::string &tok) {
    if (tok.empty()) return;
    if (tok.size() >= 100) return;  // ASCII: chars == bytes
    if (sink) sink(sink_ctx, tok);
    else tokens.push_back(tok);
  }

  void acronym(std::string tok) {
    tok = simple_fix(tok);
    size_t b = tok.find_first_not_of('.');
    size_t e = tok.find_last_not_of('.');
    tok = (b == std::string::npos) ? "" : tok.substr(b, e - b + 1);
    if (tok.find('.') != std::string::npos) {
      bool is_acr = !tok.empty();
      for (size_t i = 1; i < tok.size(); i += 2)
        if (tok[i] != '.') { is_acr = false; break; }
      if (is_acr) {
        std::string collapsed;
        for (char c : tok) if (c != '.') collapsed.push_back(c);
        add(collapsed);
      } else {
        size_t s = 0;
        for (size_t i = 0; i <= tok.size(); ++i) {
          if (i == tok.size() || tok[i] == '.') {
            if (i - s > 1) add(tok.substr(s, i - s));
            s = i + 1;
          }
        }
      }
    } else {
      add(tok);
    }
  }

  void on_token(int32_t start, int32_t end) {
    if (end <= start) return;
    std::string tok(text + start, text + end);
    switch (classify(tok)) {
      case CLEAN: add(tok); break;
      case SIMPLE:
      case COMPLEX: add(simple_fix(tok)); break;
      case ACRONYM: acronym(tok); break;
    }
  }

  // returns index of ';' ending a valid entity after '&' at pos, else -1
  int32_t entity_end(int32_t pos) {
    for (int32_t i = pos + 1; i < n; ++i) {
      char c = text[i];
      if (is_lower(c) || is_digit(c) || c == '#') continue;
      if (c == ';') return i;
      break;
    }
    return -1;
  }

  int32_t tag_name_end(int32_t start) {
    int32_t i = start;
    while (i < n && text[i] != ' ' && text[i] != '>') ++i;
    return i;
  }

  int32_t skip_comment(int32_t pos) {
    if (pos + 3 < n && memcmp(text + pos, "<!--", 4) == 0) {
      const char *f = (const char *)memmem(text + pos + 1, n - pos - 1, "-->", 3);
      return f ? (int32_t)(f - text) + 2 : n;
    }
    const char *f = (const char *)memchr(text + pos + 1, '>', n - pos - 1);
    return f ? (int32_t)(f - text) : n;
  }

  int32_t parse_end_tag(int32_t pos) {
    int32_t i = tag_name_end(pos + 2);
    std::string name(text + pos + 2, text + i);
    for (auto &ch : name) if (is_upper(ch)) ch += 32;
    if (!ignore_until.empty() && ignore_until == name) ignore_until.clear();
    while (i < n && text[i] != '>') ++i;
    return i;
  }

  // end index of one attribute (first unquoted space or '>'), or -1
  int32_t attr_end(int32_t start, int32_t tag_end) {
    bool in_quote = false, escaped = false;
    for (int32_t i = start; i <= tag_end; ++i) {
      char c = text[i];
      if ((c == '"' || c == '\'') && !escaped) {
        in_quote = !in_quote;
        if (!in_quote) return i;
      } else if (!in_quote && (c == ' ' || c == '>')) {
        return i;
      } else if (c == '\\' && !escaped) {
        escaped = true;
        continue;
      }
      escaped = false;
    }
    return -1;
  }

  int32_t parse_begin_tag(int32_t pos) {
    int32_t i = tag_name_end(pos + 1);
    std::string name(text + pos + 1, text + i);
    for (auto &ch : name) if (is_upper(ch)) ch += 32;

    bool close_it = false;
    while (i < n && text[i] == ' ') ++i;
    if (i >= n) {
      i = n;
    } else if (text[i] == '>') {
      // position lands on '>'
    } else {
      const char *f = (const char *)memchr(text + i + 1, '>', n - i - 1);
      int32_t tag_end = f ? (int32_t)(f - text) : -1;
      if (tag_end >= 0) {
        while (i < tag_end) {
          int32_t start = i;
          while (start < tag_end && text[start] == ' ') ++start;
          if (text[start] == '>') { i = start; break; }
          if (text[start] == '/' && start + 1 < n && text[start + 1] == '>') {
            i = start + 1;
            close_it = true;
            break;
          }
          int32_t end = attr_end(start, tag_end);
          if (end < 0) { i = tag_end; break; }
          i = end;
          if (i < n && (text[i] == '"' || text[i] == '\'')) ++i;
        }
      }
      // malformed (no '>'): resume right after the name, i unchanged
    }
    if ((name == "style" || name == "script") && !close_it) ignore_until = name;
    return i;
  }

  int32_t on_start_bracket(int32_t pos) {
    if (pos + 1 >= n) return n;
    char c = text[pos + 1];
    if (c == '/') return parse_end_tag(pos);
    if (!ignore_until.empty()) {
      // inside <style>/<script> only the matching end tag can change
      // state (twin of tag_tokenizer.py::_on_start_bracket)
      const char *f = (const char *)memchr(text + pos + 1, '>', n - pos - 1);
      return f ? (int32_t)(f - text) : n;
    }
    if (c == '!') return skip_comment(pos);
    if (c == '?') {
      const char *f = (const char *)memmem(text + pos + 1, n - pos - 1, "?>", 2);
      return f ? (int32_t)(f - text) : n;
    }
    return parse_begin_tag(pos);
  }

  void run() {
    init_splits();
    int32_t pos = 0, last_split = -1;
    while (pos >= 0 && pos < n) {
      char c = text[pos];
      if (c == '<') {
        if (ignore_until.empty()) on_token(last_split + 1, pos);
        pos = on_start_bracket(pos);
        last_split = pos;
      } else if (!ignore_until.empty()) {
        // skip
      } else if (c == '&') {
        on_token(last_split + 1, pos);
        last_split = pos;
        int32_t e = entity_end(pos);
        if (e >= 0) { pos = e; last_split = e; }
      } else if (split_table[(uint8_t)c]) {
        on_token(last_split + 1, pos);
        last_split = pos;
      }
      ++pos;
    }
    if (ignore_until.empty()) on_token(last_split + 1, n);
  }
};

// ---------------------------------------------------------------- porter2

inline bool p2_vowel(const std::string &w, size_t i) {
  char c = w[i];
  return c == 'a' || c == 'e' || c == 'i' || c == 'o' || c == 'u' || c == 'y';
}

bool contains_vowel(const std::string &w, size_t end) {
  for (size_t i = 0; i < end && i < w.size(); ++i)
    if (p2_vowel(w, i)) return true;
  return false;
}

void mark_regions(const std::string &w, size_t &r1, size_t &r2) {
  size_t n = w.size();
  r1 = n;
  static const char *prefixes[] = {"gener", "commun", "arsen"};
  bool special = false;
  for (const char *p : prefixes) {
    size_t pl = strlen(p);
    if (n >= pl && memcmp(w.data(), p, pl) == 0) {
      r1 = pl;
      special = true;
      break;
    }
  }
  if (!special) {
    for (size_t i = 0; i + 1 < n; ++i)
      if (p2_vowel(w, i) && !p2_vowel(w, i + 1)) { r1 = i + 2; break; }
  }
  r2 = n;
  for (size_t i = r1; i + 1 < n; ++i)
    if (p2_vowel(w, i) && !p2_vowel(w, i + 1)) { r2 = i + 2; break; }
}

bool ends_short_syllable(const std::string &w) {
  size_t n = w.size();
  if (n == 2) return p2_vowel(w, 0) && !p2_vowel(w, 1);
  if (n >= 3) {
    char last = w[n - 1];
    return p2_vowel(w, n - 2) && !p2_vowel(w, n - 3) && !p2_vowel(w, n - 1) &&
           last != 'w' && last != 'x' && last != 'Y';
  }
  return false;
}

inline bool ends_with(const std::string &w, const char *suf) {
  size_t sl = strlen(suf);
  return w.size() >= sl && memcmp(w.data() + w.size() - sl, suf, sl) == 0;
}

const std::unordered_map<std::string, std::string> &exception1() {
  static const std::unordered_map<std::string, std::string> m = {
      {"skis", "ski"},   {"skies", "sky"},  {"dying", "die"},
      {"lying", "lie"},  {"tying", "tie"},  {"idly", "idl"},
      {"gently", "gentl"}, {"ugly", "ugli"}, {"early", "earli"},
      {"only", "onli"},  {"singly", "singl"}, {"sky", "sky"},
      {"news", "news"},  {"howe", "howe"},  {"atlas", "atlas"},
      {"cosmos", "cosmos"}, {"bias", "bias"}, {"andes", "andes"},
  };
  return m;
}

const std::unordered_set<std::string> &exception2() {
  static const std::unordered_set<std::string> s = {
      "inning", "outing", "canning", "herring", "earring",
      "proceed", "exceed", "succeed"};
  return s;
}

std::string porter2(std::string w) {
  if (w.size() < 3) return w;
  {
    auto it = exception1().find(w);
    if (it != exception1().end()) return it->second;
  }
  // prelude
  if (w[0] == '\'') w.erase(0, 1);
  bool y_found = false;
  if (!w.empty() && w[0] == 'y') { w[0] = 'Y'; y_found = true; }
  for (size_t i = 1; i < w.size(); ++i)
    if (w[i] == 'y' && p2_vowel(w, i - 1)) { w[i] = 'Y'; y_found = true; }

  size_t r1, r2;
  mark_regions(w, r1, r2);

  // step 0
  if (ends_with(w, "'s'")) w.resize(w.size() - 3);
  else if (ends_with(w, "'s")) w.resize(w.size() - 2);
  else if (ends_with(w, "'")) w.resize(w.size() - 1);

  // step 1a
  if (ends_with(w, "sses")) {
    w.resize(w.size() - 2);
  } else if (ends_with(w, "ied") || ends_with(w, "ies")) {
    if (w.size() > 4) { w.resize(w.size() - 3); w += "i"; }
    else { w.resize(w.size() - 3); w += "ie"; }
  } else if (ends_with(w, "us") || ends_with(w, "ss")) {
    // nothing
  } else if (ends_with(w, "s")) {
    if (w.size() >= 2 && contains_vowel(w, w.size() - 2))
      w.resize(w.size() - 1);
  }

  if (exception2().count(w)) return w;

  // step 1b
  {
    const char *suf = nullptr;
    static const char *sufs[] = {"eedly", "ingly", "edly", "eed", "ing", "ed"};
    for (const char *s : sufs)
      if (ends_with(w, s)) { suf = s; break; }
    if (suf && (strcmp(suf, "eed") == 0 || strcmp(suf, "eedly") == 0)) {
      if (w.size() - strlen(suf) >= r1) {
        w.resize(w.size() - strlen(suf));
        w += "ee";
      }
    } else if (suf) {
      std::string stem = w.substr(0, w.size() - strlen(suf));
      if (contains_vowel(stem, stem.size())) {
        w = stem;
        if (ends_with(w, "at") || ends_with(w, "bl") || ends_with(w, "iz")) {
          w += "e";
        } else if (ends_with(w, "bb") || ends_with(w, "dd") ||
                   ends_with(w, "ff") || ends_with(w, "gg") ||
                   ends_with(w, "mm") || ends_with(w, "nn") ||
                   ends_with(w, "pp") || ends_with(w, "rr") ||
                   ends_with(w, "tt")) {
          w.resize(w.size() - 1);
        } else if (r1 >= w.size() && ends_short_syllable(w)) {
          w += "e";
        }
      }
    }
  }

  // step 1c
  if (w.size() > 2 && (w.back() == 'y' || w.back() == 'Y') &&
      !p2_vowel(w, w.size() - 2))
    w.back() = 'i';

  // step 2 (longest-of; order matters only among overlapping suffixes)
  {
    struct S { const char *suf, *repl; };
    static const S table[] = {
        {"ational", "ate"}, {"fulness", "ful"}, {"iveness", "ive"},
        {"ization", "ize"}, {"ousness", "ous"}, {"biliti", "ble"},
        {"lessli", "less"}, {"tional", "tion"}, {"alism", "al"},
        {"aliti", "al"},    {"ation", "ate"},   {"entli", "ent"},
        {"fulli", "ful"},   {"iviti", "ive"},   {"ousli", "ous"},
        {"abli", "able"},   {"alli", "al"},     {"anci", "ance"},
        {"ator", "ate"},    {"enci", "ence"},   {"izer", "ize"},
        {"bli", "ble"},
    };
    bool matched = false;
    for (const S &e : table) {
      if (ends_with(w, e.suf)) {
        matched = true;
        if (w.size() - strlen(e.suf) >= r1) {
          w.resize(w.size() - strlen(e.suf));
          w += e.repl;
        }
        break;
      }
    }
    if (!matched) {
      if (ends_with(w, "ogi")) {
        if (w.size() - 3 >= r1 && w.size() >= 4 && w[w.size() - 4] == 'l')
          w.resize(w.size() - 1);
      } else if (ends_with(w, "li")) {
        if (w.size() - 2 >= r1 && w.size() >= 3) {
          char c = w[w.size() - 3];
          if (strchr("cdeghkmnrt", c)) w.resize(w.size() - 2);
        }
      }
    }
  }

  // step 3
  {
    struct S { const char *suf, *repl; };
    static const S table[] = {
        {"ational", "ate"}, {"tional", "tion"}, {"alize", "al"},
        {"icate", "ic"},    {"iciti", "ic"},    {"ical", "ic"},
        {"ful", ""},        {"ness", ""},
    };
    bool matched = false;
    for (const S &e : table) {
      if (ends_with(w, e.suf)) {
        matched = true;
        if (w.size() - strlen(e.suf) >= r1) {
          w.resize(w.size() - strlen(e.suf));
          w += e.repl;
        }
        break;
      }
    }
    if (!matched && ends_with(w, "ative")) {
      if (w.size() - 5 >= r1 && w.size() - 5 >= r2) w.resize(w.size() - 5);
    }
  }

  // step 4
  {
    static const char *sufs[] = {"ement", "ance", "ence", "able", "ible",
                                 "ment", "ant", "ent", "ism", "ate", "iti",
                                 "ous", "ive", "ize", "al", "er", "ic"};
    bool matched = false;
    for (const char *s : sufs) {
      if (ends_with(w, s)) {
        matched = true;
        if (w.size() - strlen(s) >= r2) w.resize(w.size() - strlen(s));
        break;
      }
    }
    if (!matched && (ends_with(w, "sion") || ends_with(w, "tion"))) {
      if (w.size() - 3 >= r2) w.resize(w.size() - 3);
    }
  }

  // step 5
  if (!w.empty() && w.back() == 'e') {
    std::string head = w.substr(0, w.size() - 1);
    if (w.size() - 1 >= r2 ||
        (w.size() - 1 >= r1 && !ends_short_syllable(head)))
      w.resize(w.size() - 1);
  } else if (!w.empty() && w.back() == 'l') {
    if (w.size() - 1 >= r2 && w.size() >= 2 && w[w.size() - 2] == 'l')
      w.resize(w.size() - 1);
  }

  if (y_found)
    for (auto &c : w)
      if (c == 'Y') c = 'y';
  return w;
}

// ---------------------------------------------------------------- C API

std::unordered_set<std::string> g_stopwords;

}  // namespace

extern "C" {

void ir_set_stopwords(const char *blob, int32_t len) {
  g_stopwords.clear();
  const char *p = blob, *end = blob + len;
  while (p < end) {
    const char *nl = (const char *)memchr(p, '\n', end - p);
    if (!nl) nl = end;
    if (nl > p) g_stopwords.emplace(p, nl);
    p = nl + 1;
  }
}

// Analyze one ASCII document. Writes '\n'-joined analyzed tokens to out.
// Returns bytes written (>= 0), or -(bytes needed) if out_cap is too small.
int32_t ir_analyze(const char *text, int32_t len, char *out, int32_t out_cap) {
  Tokenizer tk;
  tk.text = text;
  tk.n = len;
  tk.run();

  // stopword filter + stem, accumulating into out
  static thread_local std::unordered_map<std::string, std::string> cache;
  int64_t written = 0;
  int64_t needed = 0;
  for (const std::string &tok : tk.tokens) {
    if (g_stopwords.count(tok)) continue;
    std::string stemmed;
    auto it = cache.find(tok);
    if (it != cache.end()) {
      stemmed = it->second;
    } else {
      stemmed = porter2(tok);
      cache.emplace(tok, stemmed);
      if (cache.size() > 50000) cache.clear();
    }
    int64_t need = (int64_t)stemmed.size() + 1;
    if (written + need <= out_cap) {
      memcpy(out + written, stemmed.data(), stemmed.size());
      out[written + stemmed.size()] = '\n';
      written += need;
    }
    needed += need;
  }
  if (needed > out_cap) return (int32_t)-needed;
  return (int32_t)written;
}

const char *ir_version() { return "tpu-ir-native-1"; }

}  // extern "C"

// ------------------------------------------------------------ corpus API
//
// Whole-corpus ingestion: TREC <DOC> record splitting, docid extraction,
// analysis, and incremental vocab construction in one pass, so Python never
// materializes per-token strings. Temp term ids are insertion-ordered; the
// Python side remaps them to sorted-vocab ids with one vectorized pass.
// Non-ASCII documents are recorded as (start, end) byte ranges for the
// Python analyzer to handle (same fallback contract as ir_analyze).

#include <cstdio>

namespace {

struct Corpus {
  std::vector<std::string> docids;
  std::vector<int64_t> doc_token_counts;
  std::vector<int32_t> token_ids;
  std::unordered_map<std::string, int32_t> vocab;
  std::vector<std::string> vocab_list;
  // raw token -> final term id (-1 = stopword): folds the stopword probe,
  // stem-cache probe, and vocab probe of the hot loop into ONE hash lookup
  // after a token's first sighting; porter2 is pure, so memoizing the whole
  // mapping is semantically identical to the 3-step path. Bounded by the
  // number of distinct raw tokens (~vocab size).
  std::unordered_map<std::string, int32_t> tok2id;
  // per skipped doc: (file_index, start, end) byte range
  std::vector<int64_t> nonascii;
  std::vector<std::string> files;

  // chunked-ingestion state: skip ranges relative to the buffer of the
  // most recent ir_corpus_add_bytes call (take_delta clears the token/doc
  // vectors, so a delta is always everything currently accumulated)
  std::vector<int64_t> delta_skips;  // (start, end) pairs

  int32_t term_id(const std::string &stemmed) {
    auto it = vocab.find(stemmed);
    if (it != vocab.end()) return it->second;
    int32_t id = (int32_t)vocab_list.size();
    vocab.emplace(stemmed, id);
    vocab_list.push_back(stemmed);
    return id;
  }

  int32_t intern_token(const std::string &tok) {
    auto it = tok2id.find(tok);
    if (it != tok2id.end()) return it->second;
    int32_t id = g_stopwords.count(tok) ? -1 : term_id(porter2(tok));
    tok2id.emplace(tok, id);
    return id;
  }
};

// Scan every complete <DOC>..</DOC> record in data[0..len) and ingest it.
// Skipped records (non-ASCII or missing docid) are appended to `skips` as
// (file_idx, start, end) triples when file_idx >= 0, else as (start, end)
// pairs (chunk mode). Returns docs ingested.
int64_t process_records(Corpus *c, const char *data, size_t len,
                        int64_t file_idx, std::vector<int64_t> *skips) {
  int64_t added = 0;
  size_t pos = 0;
  while (true) {
    const char *start =
        (const char *)memmem(data + pos, len - pos, "<DOC>", 5);
    if (!start) break;
    size_t s_off = start - data;
    const char *end = (const char *)memmem(data + s_off + 5,
                                           len - s_off - 5, "</DOC>", 6);
    if (!end) break;
    size_t e_off = end - data + 6;

    // docid between <DOCNO> and </DOCNO>, trimmed
    const char *dn =
        (const char *)memmem(data + s_off, e_off - s_off, "<DOCNO>", 7);
    std::string docid;
    if (dn) {
      const char *dne = (const char *)memmem(dn + 7, data + e_off - dn - 7,
                                             "</DOCNO>", 8);
      if (dne) {
        const char *b = dn + 7;
        const char *e2 = dne;
        while (b < e2 && (unsigned char)*b <= ' ') ++b;
        while (e2 > b && (unsigned char)e2[-1] <= ' ') --e2;
        docid.assign(b, e2);
      }
    }

    bool ascii = true;
    for (size_t i = s_off; i < e_off; ++i)
      if ((unsigned char)data[i] >= 0x80) { ascii = false; break; }

    if (!ascii || docid.empty()) {
      if (file_idx >= 0) skips->push_back(file_idx);
      skips->push_back((int64_t)s_off);
      skips->push_back((int64_t)e_off);
    } else {
      struct Sink {
        Corpus *c;
        int64_t count;
      } st{c, 0};
      Tokenizer tk;
      tk.text = data + s_off;
      tk.n = (int32_t)(e_off - s_off);
      tk.sink_ctx = &st;
      tk.sink = [](void *p, const std::string &tok) {
        Sink *s = (Sink *)p;
        int32_t id = s->c->intern_token(tok);
        if (id < 0) return;
        s->c->token_ids.push_back(id);
        ++s->count;
      };
      tk.run();
      c->docids.push_back(docid);
      c->doc_token_counts.push_back(st.count);
      ++added;
    }
    pos = e_off;
  }
  return added;
}

}  // namespace

extern "C" {

void *ir_corpus_new() { return new Corpus(); }

void ir_corpus_free(void *h) { delete (Corpus *)h; }

// Returns docs added, or -1 on IO error. Gzip files are NOT handled here
// (the Python wrapper routes them to the pure-Python reader).
int64_t ir_corpus_add_file(void *h, const char *path) {
  Corpus *c = (Corpus *)h;
  FILE *f = fopen(path, "rb");
  if (!f) return -1;
  fseek(f, 0, SEEK_END);
  long fsize = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string data(fsize, '\0');
  if (fsize && fread(&data[0], 1, fsize, f) != (size_t)fsize) {
    fclose(f);
    return -1;
  }
  fclose(f);
  int64_t file_idx = (int64_t)c->files.size();
  c->files.emplace_back(path);
  return process_records(c, data.data(), data.size(), file_idx,
                         &c->nonascii);
}

// ---- chunked ingestion (streaming builds) ----
//
// The caller feeds byte buffers whose records are complete (split the
// stream at a </DOC> boundary), then drains each delta: token ids + doc
// lens + docids added since the previous take. Skipped (non-ASCII /
// docid-less) records are returned as (start, end) offsets into the buffer
// of THIS add_bytes call, so the caller must take the delta before feeding
// the next chunk. The incremental vocab spans the whole corpus; ids in
// deltas are stable temp ids remapped to sorted order by the caller at the
// end (ir_corpus_stats/ir_corpus_export semantics unchanged).

int64_t ir_corpus_add_bytes(void *h, const char *data, int64_t len) {
  Corpus *c = (Corpus *)h;
  return process_records(c, data, (size_t)len, -1, &c->delta_skips);
}

// out4: n_docs, n_tokens, docids_blob_bytes, n_skip_pairs (delta only)
void ir_corpus_delta_stats(void *h, int64_t *out4) {
  Corpus *c = (Corpus *)h;
  int64_t docid_bytes = 0;
  for (auto &s : c->docids) docid_bytes += (int64_t)s.size() + 1;
  out4[0] = (int64_t)c->docids.size();
  out4[1] = (int64_t)c->token_ids.size();
  out4[2] = docid_bytes;
  out4[3] = (int64_t)(c->delta_skips.size() / 2);
}

// Export the delta and release its token/doc storage (vocab is kept).
void ir_corpus_take_delta(void *h, int32_t *ids, int64_t *doc_lens,
                          char *docids_blob, int64_t *skips_out) {
  Corpus *c = (Corpus *)h;
  memcpy(ids, c->token_ids.data(), c->token_ids.size() * sizeof(int32_t));
  memcpy(doc_lens, c->doc_token_counts.data(),
         c->doc_token_counts.size() * sizeof(int64_t));
  char *p = docids_blob;
  for (auto &s : c->docids) {
    memcpy(p, s.data(), s.size());
    p += s.size();
    *p++ = '\n';
  }
  if (!c->delta_skips.empty())
    memcpy(skips_out, c->delta_skips.data(),
           c->delta_skips.size() * sizeof(int64_t));
  c->delta_skips.clear();
  // bounded memory: drop the exported tokens/docids, keep only the vocab
  c->token_ids.clear();
  c->doc_token_counts.clear();
  c->docids.clear();
}

// Intern one ALREADY-ANALYZED term (vocab insert only — no stopword filter
// or stemming, which the Python fallback analyzer has already applied) into
// the corpus-wide vocab; for the rare fallback docs in chunk mode.
int32_t ir_corpus_intern_term(void *h, const char *term, int32_t len) {
  Corpus *c = (Corpus *)h;
  return c->term_id(std::string(term, (size_t)len));
}

// vocab blob size alone (chunk mode drains docs/tokens via deltas, so
// ir_corpus_stats' other fields are not meaningful there)
int64_t ir_corpus_vocab_bytes(void *h) {
  Corpus *c = (Corpus *)h;
  int64_t vocab_bytes = 0;
  for (auto &s : c->vocab_list) vocab_bytes += (int64_t)s.size() + 1;
  return vocab_bytes;
}

void ir_corpus_vocab_export(void *h, char *vocab_blob) {
  Corpus *c = (Corpus *)h;
  char *p = vocab_blob;
  for (auto &s : c->vocab_list) {
    memcpy(p, s.data(), s.size());
    p += s.size();
    *p++ = '\n';
  }
}

// out8: n_docs, n_tokens, vocab_size, docids_blob_bytes, vocab_blob_bytes,
//       n_nonascii_triples, 0, 0
void ir_corpus_stats(void *h, int64_t *out8) {
  Corpus *c = (Corpus *)h;
  int64_t docid_bytes = 0, vocab_bytes = 0;
  for (auto &s : c->docids) docid_bytes += (int64_t)s.size() + 1;
  for (auto &s : c->vocab_list) vocab_bytes += (int64_t)s.size() + 1;
  out8[0] = (int64_t)c->docids.size();
  out8[1] = (int64_t)c->token_ids.size();
  out8[2] = (int64_t)c->vocab_list.size();
  out8[3] = docid_bytes;
  out8[4] = vocab_bytes;
  out8[5] = (int64_t)(c->nonascii.size() / 3);
  out8[6] = 0;
  out8[7] = 0;
}

// Caller allocates per ir_corpus_stats sizes. Blobs are '\n'-joined.
void ir_corpus_export(void *h, int32_t *ids, int64_t *doc_lens,
                      char *docids_blob, char *vocab_blob,
                      int64_t *nonascii_out) {
  Corpus *c = (Corpus *)h;
  memcpy(ids, c->token_ids.data(), c->token_ids.size() * sizeof(int32_t));
  memcpy(doc_lens, c->doc_token_counts.data(),
         c->doc_token_counts.size() * sizeof(int64_t));
  char *p = docids_blob;
  for (auto &s : c->docids) {
    memcpy(p, s.data(), s.size());
    p += s.size();
    *p++ = '\n';
  }
  p = vocab_blob;
  for (auto &s : c->vocab_list) {
    memcpy(p, s.data(), s.size());
    p += s.size();
    *p++ = '\n';
  }
  if (!c->nonascii.empty())
    memcpy(nonascii_out, c->nonascii.data(),
           c->nonascii.size() * sizeof(int64_t));
}

}  // extern "C"
