"""Headline benchmark: index a reference-scale synthetic TREC corpus and
answer a 10k batched query load.

Reference baseline (BASELINE.md): the PA1 inverted-index build processed
8,761 TREC docs (23.9 MB) in 51 s on the course Hadoop cluster -> ~172 docs/s.
Query latency was never measured there (interactive REPL only), so docs/sec
indexed is the headline metric and batched queries/sec is reported alongside.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

BASELINE_DOCS_PER_SEC = 8761 / 51.0  # reference PA1 job _0010

# word-shape pool: mixed lengths, zipf-ish usage like English text
VOCAB_SIZE = 30_000
DOC_COUNT = 8_761
TARGET_BYTES = 23_950_858


def make_corpus(path: str, seed: int = 0, *, n_docs: int | None = None,
                target_bytes: int | None = None,
                vocab_size: int | None = None) -> int:
    n_docs = DOC_COUNT if n_docs is None else n_docs
    target_bytes = TARGET_BYTES if target_bytes is None else target_bytes
    vocab_size = VOCAB_SIZE if vocab_size is None else vocab_size
    rng = np.random.default_rng(seed)
    letters = np.array(list("abcdefghijklmnopqrstuvwxyz"))
    lengths = rng.integers(3, 11, vocab_size)
    words = np.array(["".join(rng.choice(letters, l)) for l in lengths])
    zipf_p = 1.0 / np.arange(1, vocab_size + 1)
    zipf_p /= zipf_p.sum()

    avg_doc_words = target_bytes // n_docs // 8  # ~8 bytes/word incl space
    n_words_per_doc = rng.integers(avg_doc_words // 2,
                                   avg_doc_words * 3 // 2, n_docs)
    all_ids = rng.choice(vocab_size, int(n_words_per_doc.sum()), p=zipf_p)
    total = 0
    pos = 0
    with open(path, "w") as f:
        for i in range(n_docs):
            n = int(n_words_per_doc[i])
            body = " ".join(words[all_ids[pos : pos + n]])
            pos += n
            rec = (f"<DOC>\n<DOCNO> SYN-{i:06d} </DOCNO>\n<TEXT>\n{body}\n"
                   f"</TEXT>\n</DOC>\n")
            f.write(rec)
            total += len(rec)
    return total


def make_quality_corpus(path: str, n_docs: int, n_queries: int,
                        seed: int = 7, with_prox: bool = False):
    """Passage corpus with GRADED planted relevance that splits the scorers.

    Each query i is two entity terms unique to it, with a relevant passage
    (grade 2) and distractors (grade 1) built so the three scorers come
    apart — the round-1 generator saturated at MRR 1.0 for everything and
    could not detect a regression. Query types cycle:

    - type 0 (verbose doc): relevant has both terms at tf 2 in a normal
      passage; a distractor has both at tf 3 buried in a ~460-word doc.
      TF-IDF has NO length normalization, so the verbose doc's higher tf
      wins; BM25's length norm and the cosine stage's doc norm both
      punish it. => splits TF-IDF below BM25 (and the rerank).
    - type 1 (norm tie): a distractor with the SAME query-term tfs and
      the SAME length as the relevant doc, but its padding is 40 distinct
      rare fillers where the relevant doc repeats one filler. TF-IDF and
      BM25 tie exactly (winner = lower docno, random); the cosine stage's
      doc-norm breaks the tie toward the lighter (relevant) vector.
      => splits BM25/TF-IDF below the rerank.
    - type 2 (legit stronger doc): a grade-1 distractor with both terms at
      tf 3 in a shorter doc beats the relevant doc under EVERY scorer,
      capping all metrics strictly below 1.
    - type 3 (idf canary): query = rare entity + a planted COMMON topic
      word (appears tf 1 in ~4% of the corpus). The relevant doc has the
      rare term once; distractors carry only the common word at higher tf.
      Only df-aware weighting ranks the relevant doc first — flatten idf
      and the common word drowns the query, collapsing TF-IDF and the
      rerank while BM25 (its own idf) stands, which breaks the gate's
      ordering. This is what makes a broken idf FAIL the bench.

    Returns (queries, rel_docnos, grades) — grades[qi] maps docno->grade
    for NDCG. Docids are zero-padded in generation order, so docno ==
    doc index + 1 after sorted numbering.

    `with_prox=True` additionally plants n_queries//4 PROX-TIE pairs and
    returns them as a fourth element (prox_queries, prox_rel_docnos):
    the relevant doc holds the two query entities ADJACENT, a distractor
    holds them separated by its filler run — same tfs, same length, same
    norm, so TF-IDF, BM25 and the cosine rerank all tie EXACTLY and the
    tie breaks by docno order, which is rigged toward the distractor.
    Only the positions-based proximity boost can rank the relevant doc
    first; the measured MRR lift on this subset is the bench's evidence
    that the proximity feature works (VERDICT r2 item 4).
    """
    rng = np.random.default_rng(seed)
    letters = np.array(list("abcdefghijklmnopqrstuvwxyz"))
    bg_vocab = 40_000
    lengths = rng.integers(4, 10, bg_vocab)
    bg_words = np.array(["".join(rng.choice(letters, l)) for l in lengths])
    zipf_p = 1.0 / np.arange(1, bg_vocab + 1)
    zipf_p /= zipf_p.sum()

    def entity(i, which):  # unique, analyzer-stable
        return f"xx{which}{i:05d}ent"

    COMMON = "qqcommontopic"  # planted into ~4% of unplanted docs below

    doc_words: dict[int, list[str]] = {}
    no_bg: set[int] = set()   # docs whose token lists must match exactly
    queries, rel_docnos, grades = [], [], []
    n_prox = max(n_queries // 4, 1) if with_prox else 0
    slots = rng.choice(n_docs, n_queries * 3 + n_prox * 2, replace=False)
    prox_queries: list[str] = []
    prox_rel: list[int] = []
    for pi in range(n_prox):
        a, b = (int(s) for s in slots[n_queries * 3 + 2 * pi:
                                      n_queries * 3 + 2 * pi + 2])
        dis, rel = min(a, b), max(a, b)  # tie breaks toward the distractor
        e1, e2 = entity(pi, "p"), entity(pi, "q")
        K = 30
        doc_words[rel] = [e1, e2] + [f"pp{pi:05d}r"] * K
        doc_words[dis] = [e1] + [f"pp{pi:05d}d"] * K + [e2]
        no_bg.update((rel, dis))
        prox_queries.append(f"{e1} {e2}")
        prox_rel.append(rel + 1)
    for qi in range(n_queries):
        e1, e2 = entity(qi, "a"), entity(qi, "b")
        rel, d1, d2 = (int(s) for s in slots[3 * qi : 3 * qi + 3])
        kind = qi % 4
        if kind == 0:    # verbose doc: 2*(1+ln 3) > 2*(1+ln 2), length ignored
            doc_words[rel] = [e1] * 2 + [e2] * 2
            doc_words[d1] = ([e1] * 3 + [e2] * 3
                             + list(bg_words[rng.integers(0, bg_vocab, 400)]))
            doc_words[d2] = [e2] * 1
        elif kind == 1:  # exact tie broken only by the cosine doc norm
            filler = f"zz{qi:05d}fil"
            doc_words[rel] = ([e1] * 2 + [e2] * 2 + [filler] * 40)
            doc_words[d1] = ([e1] * 2 + [e2] * 2
                             + [f"zz{qi:05d}d{j:02d}" for j in range(40)])
            doc_words[d2] = [e1] * 1  # weak single-term doc
            no_bg.update((rel, d1))
        elif kind == 2:  # legitimately stronger grade-1 distractor
            doc_words[rel] = [e1] * 2 + [e2] * 2
            doc_words[d1] = [e1] * 3 + [e2] * 3
            doc_words[d2] = [e2] * 1
            no_bg.add(d1)
        else:            # idf canary: rare entity vs planted common word
            doc_words[rel] = [e1] * 1
            doc_words[d1] = [COMMON] * 3
            doc_words[d2] = [COMMON] * 2
            queries.append(f"{e1} {COMMON}")
            rel_docnos.append(rel + 1)
            grades.append({rel + 1: 2, d1 + 1: 1, d2 + 1: 1})
            continue
        queries.append(f"{e1} {e2}")
        rel_docnos.append(rel + 1)
        grades.append({rel + 1: 2, d1 + 1: 1, d2 + 1: 1})

    # one vectorized zipf draw for every document's background words
    # (per-doc rng.choice with a 40k-entry p vector is seconds of waste)
    n_bg_per_doc = rng.integers(40, 80, n_docs)
    all_bg = rng.choice(bg_vocab, int(n_bg_per_doc.sum()), p=zipf_p)
    offsets = np.concatenate([[0], np.cumsum(n_bg_per_doc)])
    with open(path, "w") as f:
        for i in range(n_docs):
            planted = doc_words.get(i)
            if i in no_bg:
                words = list(planted)
            else:
                words = list(bg_words[all_bg[offsets[i] : offsets[i + 1]]])
                if planted:
                    pos = rng.integers(0, len(words) + 1, len(planted))
                    for p, w in zip(sorted(pos, reverse=True), planted):
                        words.insert(int(p), w)
                elif i % 25 == 7:  # make COMMON genuinely common (df ~ 4%)
                    words.append(COMMON)
            body = " ".join(words)
            f.write(f"<DOC>\n<DOCNO> MSM-{i:06d} </DOCNO>\n<TEXT>\n{body}\n"
                    f"</TEXT>\n</DOC>\n")
    if with_prox:
        return (queries, np.array(rel_docnos, np.int64), grades,
                (prox_queries, np.array(prox_rel, np.int64)))
    return queries, np.array(rel_docnos, np.int64), grades


def _mrr_at_k(rel_docnos: np.ndarray, got_docnos: np.ndarray) -> float:
    rr = 0.0
    for qi in range(len(rel_docnos)):
        where = np.nonzero(got_docnos[qi] == rel_docnos[qi])[0]
        if len(where):
            rr += 1.0 / (int(where[0]) + 1)
    return round(rr / len(rel_docnos), 4)


def _ndcg_at_k(grades: list, got_docnos: np.ndarray, k: int = 10) -> float:
    """Graded NDCG@k with gains 2^g - 1 (the standard web-search form)."""
    total = 0.0
    for qi, g in enumerate(grades):
        dcg = sum((2.0 ** g.get(int(d), 0) - 1) / np.log2(r + 2)
                  for r, d in enumerate(got_docnos[qi][:k]))
        ideal = sorted(g.values(), reverse=True)[:k]
        idcg = sum((2.0 ** gv - 1) / np.log2(r + 2)
                   for r, gv in enumerate(ideal))
        total += dcg / idcg if idcg > 0 else 0.0
    return round(total / len(grades), 4)


def _mrr_binary(grades: list, got_docnos: np.ndarray) -> float:
    """MRR under trec_eval's binary-relevance convention: the first
    ranked doc with ANY positive grade counts (unlike _mrr_at_k, which
    tracks only the planted grade-2 doc)."""
    rr = 0.0
    for qi, g in enumerate(grades):
        for r, d in enumerate(got_docnos[qi]):
            if g.get(int(d), 0) > 0:
                rr += 1.0 / (r + 1)
                break
    return round(rr / len(grades), 4)


def _eval_loop_roundtrip(tmp: str, index_dir: str, queries, grades,
                         bm25_docnos10,
                         m_eval_cap: int = 300) -> dict:
    """topics -> `tpu-ir search --topics --trec-run` -> run file ->
    evaluate_run(qrels). Returns the loop's metrics plus an "eval_loop"
    verdict that must be "ok": the run-file MRR@10 and (exp-gain) NDCG@10
    must equal the in-process BM25 numbers on the same query subset."""
    import contextlib
    import io

    from tpu_ir.cli import main as cli_main
    from tpu_ir.search.evaluate import evaluate_run, read_qrels, read_run

    m_eval = min(m_eval_cap, len(queries))
    topics = os.path.join(tmp, "topics.trec")
    with open(topics, "w") as f:
        for qi in range(m_eval):
            f.write(f"<top>\n<num> Number: {qi + 1}\n"
                    f"<title> {queries[qi]}\n</top>\n")
    qrels_path = os.path.join(tmp, "qrels.txt")
    with open(qrels_path, "w") as f:
        for qi in range(m_eval):
            for docno, grade in grades[qi].items():
                f.write(f"{qi + 1} 0 MSM-{docno - 1:06d} {grade}\n")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(["search", index_dir, "--topics", topics,
                       "--scoring", "bm25", "--k", "10",
                       "--trec-run", "bench"])
    run_path = os.path.join(tmp, "run.txt")
    with open(run_path, "w") as f:
        f.write(buf.getvalue())
    if rc != 0:
        return {"eval_loop": f"search exited {rc}"}
    ev = evaluate_run(read_run(run_path), read_qrels(qrels_path),
                      complete=True, exp_gains=True)
    want_mrr = _mrr_binary(grades[:m_eval], bm25_docnos10[:m_eval])
    want_ndcg = _ndcg_at_k(grades[:m_eval], bm25_docnos10[:m_eval])
    ok = (ev.get("queries") == m_eval
          and abs(ev["mrr"] - want_mrr) < 1e-3
          and abs(ev["ndcg_at_10"] - want_ndcg) < 1e-3)
    return {
        "eval_loop": "ok" if ok else (
            f"mismatch: run mrr={ev.get('mrr')} vs {want_mrr}, "
            f"ndcg={ev.get('ndcg_at_10')} vs {want_ndcg}, "
            f"queries={ev.get('queries')} vs {m_eval}"),
        "eval_loop_queries": m_eval,
        "eval_loop_mrr": ev.get("mrr", -1.0),
        "eval_loop_ndcg_at_10": ev.get("ndcg_at_10", -1.0),
        "eval_loop_map": ev.get("map", -1.0),
    }


def run_stdlib_eval(tmp: str) -> dict:
    """Real-corpus quality run (VERDICT r4 next #3): the in-repo frozen
    collection of CPython stdlib module documentation (data/stdlib/ —
    144 docs of third-party text, 80 hand-judged topics with graded
    qrels) through the full standard loop: index build -> TREC topics ->
    CLI --trec-run run files -> evaluate_run against the qrels. Unlike
    the synthetic msmarco gate, neither the text nor the judgments were
    generated by this framework."""
    import contextlib
    import io

    from tpu_ir.cli import main as cli_main
    from tpu_ir.search.evaluate import evaluate_run, read_qrels, read_run

    data = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "data", "stdlib")
    if not os.path.isdir(data):
        return {"real_eval": "data/stdlib missing"}
    idx = os.path.join(tmp, "stdlib-idx")
    # redirect: the index command prints the metadata JSON, which would
    # pollute the bench's one-JSON-line stdout contract
    with contextlib.redirect_stdout(io.StringIO()):
        rc = cli_main(["index", os.path.join(data, "corpus.trec"), idx,
                       "--backend", "cpu", "--shards", "2",
                       "--no-chargrams"])
    if rc != 0:
        return {"real_eval": f"index exited {rc}"}
    qrels = read_qrels(os.path.join(data, "qrels.txt"))
    out: dict = {"real_eval": "ok", "real_corpus": "cpython-stdlib-docs"}
    for tag, extra in (("bm25", []), ("rerank", ["--rerank", "100"])):
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli_main(["search", idx, "--backend", "cpu", "--topics",
                           os.path.join(data, "topics.trec"),
                           "--scoring", "bm25", "--k", "10",
                           "--trec-run", "bench"] + extra)
        if rc != 0:
            return {"real_eval": f"search({tag}) exited {rc}"}
        run_path = os.path.join(tmp, f"stdlib-run-{tag}.txt")
        with open(run_path, "w") as f:
            f.write(buf.getvalue())
        ev = evaluate_run(read_run(run_path), qrels, complete=True,
                          exp_gains=True)
        out[f"real_{tag}_mrr"] = ev["mrr"]
        out[f"real_{tag}_ndcg_at_10"] = ev["ndcg_at_10"]
        out[f"real_{tag}_map"] = ev["map"]
        out["real_queries"] = ev["queries"]
    return out


_STDLIB_EVAL_CODE = """
import json, sys, tempfile
sys.path.insert(0, {bench_dir!r})
import bench
with tempfile.TemporaryDirectory() as tmp:
    out = bench.run_stdlib_eval(tmp)
print("STDLIB_JSON=" + json.dumps(out))
"""


def run_stdlib_eval_subprocess() -> dict:
    """run_stdlib_eval in its own interpreter, CPU-pinned from the env.

    The eval drives the CLI with --backend cpu, and cli._apply_backend
    deliberately repins the WHOLE process (jax_platforms + backend
    factories + clear_backends) — in-process it would silently migrate
    every subsequent bench measurement off the TPU while the artifact
    still says backend=tpu. Only the JSON crosses back."""
    import subprocess

    bench_dir = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             _STDLIB_EVAL_CODE.format(bench_dir=bench_dir)],
            capture_output=True, text=True, timeout=1800, env=env)
        for line in r.stdout.splitlines():
            if line.startswith("STDLIB_JSON="):
                return json.loads(line.split("=", 1)[1])
        return {"real_eval": f"subprocess produced no result "
                             f"(rc={r.returncode}): {r.stderr[-200:]}"}
    except (subprocess.SubprocessError, OSError, ValueError) as e:
        return {"real_eval": f"subprocess failed: {e}"[:200]}


# floors for the real-corpus eval: far below the measured values
# (BM25 MRR 0.93 / NDCG@10 0.79 at freeze time) but far above what a
# broken analyzer or scoring regression could reach
_REAL_MRR_FLOOR = 0.7
_REAL_NDCG_FLOOR = 0.6

# minimum msmarco query count for the gate's margins to be meaningful
_GATE_MIN_QUERIES = 200


def quality_gate(m: dict) -> list[str]:
    """The discriminative-power contract: every metric strictly inside
    (0, 1) and rerank > BM25 > TF-IDF with real margins. A scoring
    regression (e.g. broken idf) collapses the ordering and fails here."""
    bad = []
    for key in ("tfidf_mrr_at_10", "bm25_mrr_at_10", "rerank_mrr_at_10",
                "tfidf_ndcg_at_10", "bm25_ndcg_at_10", "rerank_ndcg_at_10"):
        if not 0.0 < m[key] < 1.0:
            bad.append(f"{key}={m[key]} outside (0, 1)")
    if not m["tfidf_mrr_at_10"] + 0.05 < m["bm25_mrr_at_10"]:
        bad.append("bm25 does not beat tfidf by >= 0.05 MRR")
    if not m["bm25_mrr_at_10"] + 0.03 < m["rerank_mrr_at_10"]:
        bad.append("rerank does not beat bm25 by >= 0.03 MRR")
    if not m["tfidf_ndcg_at_10"] < m["bm25_ndcg_at_10"] \
            < m["rerank_ndcg_at_10"]:
        bad.append("NDCG ordering tfidf < bm25 < rerank violated")
    if m.get("real_eval") == "ok":
        # the real-corpus floors: hand-judged qrels over third-party
        # text — a collapsed analyzer or idf cannot stay above these
        if m["real_bm25_mrr"] < _REAL_MRR_FLOOR:
            bad.append(f"real-corpus BM25 MRR {m['real_bm25_mrr']} "
                       f"below {_REAL_MRR_FLOOR}")
        if m["real_bm25_ndcg_at_10"] < _REAL_NDCG_FLOOR:
            bad.append(f"real-corpus BM25 NDCG@10 "
                       f"{m['real_bm25_ndcg_at_10']} below "
                       f"{_REAL_NDCG_FLOOR}")
    if "prox_rerank_mrr_prox_subset" in m:
        # the prox-tie pairs tie exactly for every bag-of-words stage and
        # break toward the distractor; a working proximity boost must
        # move the subset's MRR decisively (0.5 -> ~1.0 by construction)
        if not (m["prox_rerank_mrr_prox_subset"]
                >= m["rerank_mrr_prox_subset"] + 0.2):
            bad.append("proximity boost does not lift the prox-tie "
                       "subset MRR by >= 0.2")
    return bad


def run_msmarco(args) -> dict:
    """Retrieval-quality config: graded planted relevance scored by all
    three scorers (TF-IDF / BM25 / two-stage rerank), MRR@10 + NDCG@10
    each, plus top-1000 candidate recall. The quality_gate asserts the
    discriminative ordering rerank > BM25 > TF-IDF with every value
    strictly inside (0, 1) — a scoring regression fails the gate."""
    from tpu_ir.index import build_index
    from tpu_ir.search import Scorer

    n_docs = 50_000
    n_queries = min(args.queries or 2_000, n_docs // 4)  # planted slots
    with tempfile.TemporaryDirectory() as tmp:
        corpus = os.path.join(tmp, "corpus.trec")
        queries, rel_docnos, grades, prox = make_quality_corpus(
            corpus, n_docs, n_queries, with_prox=True)
        index_dir = os.path.join(tmp, "index")
        t0 = time.perf_counter()
        # positions=True: the proximity-lift measurement below needs the
        # format-v2 position runs
        build_index([corpus], index_dir, k=1, chargram_ks=[],
                    num_shards=10, compute_chargrams=False, positions=True)
        build_s = time.perf_counter() - t0

        scorer = Scorer.load(index_dir, layout="auto")
        q_ids = scorer.analyze_queries(queries, max_terms=4)

        metrics: dict[str, float] = {}
        speeds: dict[str, float] = {}
        docnos_by_scoring: dict[str, np.ndarray] = {}
        scorer_scores_by_scoring: dict[str, np.ndarray] = {}
        for scoring in ("tfidf", "bm25"):
            scorer.topk(q_ids, k=10, scoring=scoring)  # compile
            t0 = time.perf_counter()
            scores10, docnos10 = scorer.topk(q_ids, k=10, scoring=scoring)
            dt = time.perf_counter() - t0
            docnos_by_scoring[scoring] = docnos10
            scorer_scores_by_scoring[scoring] = scores10
            metrics[f"{scoring}_mrr_at_10"] = _mrr_at_k(rel_docnos, docnos10)
            metrics[f"{scoring}_ndcg_at_10"] = _ndcg_at_k(grades, docnos10)
            speeds[f"{scoring}_queries_per_sec"] = round(n_queries / dt, 1)
        bm25_docnos10 = docnos_by_scoring["bm25"]

        # MaxScore parity gate (VERDICT r4 next #1 done-bar): pruning must
        # be INVISIBLE — the same top-10, per query, for both scorers.
        # Tie-tolerant: the two paths accumulate f32 in different orders,
        # so docno swaps are allowed only where the score vectors agree
        # within rounding (genuinely tied docs); anything else fails.
        prune_info: dict = {}
        if scorer.layout == "sparse" and scorer.prune:
            prev_prune = scorer.prune
            mismatches = 0
            try:
                scorer.prune = False
                for scoring, docnos10 in docnos_by_scoring.items():
                    s_on, d_on = scorer_scores_by_scoring[scoring], docnos10
                    s_off, d_off = scorer.topk(q_ids, k=10, scoring=scoring)
                    diff = (d_off != d_on).any(axis=1)
                    tied = np.isclose(np.asarray(s_off), np.asarray(s_on),
                                      rtol=1e-4, atol=1e-6).all(axis=1)
                    mismatches += int((diff & ~tied).sum())
            finally:
                scorer.prune = prev_prune
            prune_info = {
                "prune_parity": ("ok" if mismatches == 0
                                 else f"{mismatches} queries differ"),
                **scorer.prune_diag(q_ids),
            }

        # full standard eval loop (VERDICT r2 next #7): TREC topics file
        # -> CLI --trec-run run file -> evaluate_run against qrels. The
        # loop must REPRODUCE the in-process BM25 MRR@10/NDCG@10 on the
        # same query subset exactly — it exercises topics parsing, batch
        # search, run emission, and both eval readers end to end.
        eval_out = _eval_loop_roundtrip(
            tmp, index_dir, queries, grades, bm25_docnos10)
        # real-corpus quality run, next to the synthetic gate: in-repo
        # CPython-docs collection + hand-judged qrels (VERDICT r4 #3).
        # In a SUBPROCESS: the eval pins its process to the CPU backend
        # (the CLI's --backend is process-wide), which would silently
        # move every later msmarco measurement off the TPU
        real_out = run_stdlib_eval_subprocess()
        metrics.update({k: v for k, v in real_out.items()
                        if isinstance(v, float)})
        metrics["real_eval"] = real_out.get("real_eval", "missing")
        eval_out.update(real_out)

        m = min(256, n_queries)
        from tpu_ir.obs import get_registry

        def _blockmax_delta(before, after):
            """Realized block-max skip fraction over a measured window
            (blocks_masked / blocks_considered; None when the kernels
            never engaged — e.g. TPU_IR_BLOCKMAX=0 control runs)."""
            cons = (after.get("blockmax.blocks_considered", 0)
                    - before.get("blockmax.blocks_considered", 0))
            if cons <= 0:
                return None
            masked = (after.get("blockmax.blocks_masked", 0)
                      - before.get("blockmax.blocks_masked", 0))
            return round(masked / cons, 4)

        c0 = dict(get_registry().snapshot()["counters"])
        t0 = time.perf_counter()
        scorer.topk(q_ids[:m], k=1000, scoring="bm25")  # compile
        cold_s = time.perf_counter() - t0
        c1 = dict(get_registry().snapshot()["counters"])
        t0 = time.perf_counter()
        _, docnos1k = scorer.topk(q_ids[:m], k=1000, scoring="bm25")
        cand_s = time.perf_counter() - t0
        c2 = dict(get_registry().snapshot()["counters"])
        skip_cold = _blockmax_delta(c0, c1)
        skip_warm = _blockmax_delta(c1, c2)
        recall1k = float(np.mean([
            rel_docnos[qi] in docnos1k[qi] for qi in range(m)]))

        # stage 2: cosine TF-IDF rerank over BM25 top-1000 candidates
        # (scored over the SAME query set as the single-stage scorers so
        # the MRR/NDCG comparison is apples to apples)
        scorer.rerank_topk(q_ids, k=10, candidates=1000)  # compile
        t0 = time.perf_counter()
        _, rr_docnos = scorer.rerank_topk(q_ids, k=10, candidates=1000)
        rerank_s = time.perf_counter() - t0
        metrics["rerank_mrr_at_10"] = _mrr_at_k(rel_docnos, rr_docnos)
        metrics["rerank_ndcg_at_10"] = _ndcg_at_k(grades, rr_docnos)
        speeds["rerank_queries_per_sec"] = round(n_queries / rerank_s, 1)

        # proximity lift (VERDICT r2 item 4 "measurably improves"): on
        # the prox-tie pairs every bag-of-words stage ties EXACTLY and
        # the tie is rigged toward the distractor; only the positions
        # boost can put the relevant doc first. Plain rerank MRR on the
        # subset should sit near 0.5, prox near 1.0.
        prox_queries, prox_rel = prox
        def subset_mrr(results):
            got = np.array(
                [[dn for dn, _ in r[:10]] + [0] * (10 - min(len(r), 10))
                 for r in results], np.int64)
            return _mrr_at_k(prox_rel, got)
        base = scorer.search_batch(prox_queries, k=10, rerank=1000,
                                   return_docids=False)
        boosted = scorer.search_batch(prox_queries, k=10, rerank=1000,
                                      prox=True, return_docids=False)
        metrics["prox_subset_queries"] = len(prox_queries)
        metrics["rerank_mrr_prox_subset"] = subset_mrr(base)
        metrics["prox_rerank_mrr_prox_subset"] = subset_mrr(boosted)

        # the gate's fixed margins (0.05 / 0.03 MRR) assume all four query
        # types present in balance AND enough queries that per-query MRR
        # quantization (a handful of coin-flip "norm tie" rankings) cannot
        # eat a margin: at n=18 a healthy run fails the 0.03 margin by
        # 0.002. Enforce only from 200 queries (50+ per type, one rank
        # flip moves MRR by <= 0.005); below that, report but don't gate.
        gate = (quality_gate(metrics) if n_queries >= _GATE_MIN_QUERIES
                else [f"skipped: needs >= {_GATE_MIN_QUERIES} queries"])

    return {
        "metric": "rerank_ndcg_at_10",
        "value": metrics["rerank_ndcg_at_10"],
        "unit": "ndcg",
        # vs the reference's own scoring formula (TF-IDF is all it had) on
        # the same corpus: the quality win of the full two-stage pipeline
        "vs_baseline": round(metrics["rerank_ndcg_at_10"]
                             / max(metrics["tfidf_ndcg_at_10"], 1e-9), 3),
        "corpus_docs": n_docs,
        "queries": n_queries,
        # cold build: includes first-time XLA compiles for this config's
        # shapes (the ref config's warmed docs/s is the throughput headline)
        "index_wall_s_cold": round(build_s, 2),
        **metrics,
        **speeds,
        "top1000_queries_per_sec": round(m / cand_s, 1),
        # deep-k headline twins (ISSUE 13): the warmed deep top-k rate
        # under its own name for the sentry, the cold (first-dispatch,
        # compile included) rate, and the realized block-max skip
        # fraction over each window
        "topk1000_qps": round(m / cand_s, 1),
        "topk1000_qps_cold": round(m / cold_s, 1),
        "blockmax_skip_block_fraction": skip_warm,
        "blockmax_skip_block_fraction_cold": skip_cold,
        "top1000_recall": round(recall1k, 4),
        "quality_gate": "ok" if not gate else "; ".join(gate),
        "quality_gate_enforced": n_queries >= _GATE_MIN_QUERIES,
        **eval_out,
        **prune_info,
        **profile_breakdown(),
        "layout": scorer.layout,
        "config": "msmarco",
    }


def _recall_at_10(scorer, q_ids: np.ndarray, got_docnos: np.ndarray) -> float:
    """Exhaustive host-side TF-IDF oracle over the CSR postings."""
    pt, pd, ptf = scorer._pairs
    n = scorer.meta.num_docs
    df = np.asarray(scorer.df)
    hits = total = 0
    for qi in range(q_ids.shape[0]):
        scores = np.zeros(n + 1)
        for tid in q_ids[qi]:
            if tid < 0 or df[tid] == 0:
                continue
            sel = pt == tid
            idf = np.log10(n / df[tid])
            scores[pd[sel]] += (1.0 + np.log(ptf[sel])) * idf
        pos = np.nonzero(scores > 0)[0]
        if len(pos) == 0:
            continue
        expect = min(10, len(pos))
        thr = np.sort(scores[pos])[::-1][expect - 1]
        got = [int(d) for d in got_docnos[qi] if d > 0]
        # tie-tolerant: any doc scoring >= the oracle's 10th-best counts
        hits += sum(1 for d in got if scores[d] >= thr - 1e-9)
        total += expect
    return round(hits / total, 4) if total else 1.0


#: every device array a loaded Scorer may hold, by attribute name. The
#: single definition of "the load is complete" — the cold-load parent,
#: the warm-load child, and experiments/warm_load_profile.py all block
#: on serving_arrays(); hand-copied lists here previously risked the
#: cold/warm split comparing loads of different completeness when the
#: serving layout gains or renames an array.
SERVING_ARRAY_NAMES = ("hot_tfs", "doc_matrix", "hot_rank", "tier_of",
                       "row_of", "tier_docs", "tier_tfs")


def serving_arrays(s):
    """The Scorer's resident device arrays (df/doc_len always; layout
    arrays when the layout defines them)."""
    arrays = [s.df, s.doc_len] + [getattr(s, n, None)
                                  for n in SERVING_ARRAY_NAMES]
    return [a for a in arrays if a is not None]


_WARM_LOAD_CODE = """
import json, sys, time
t0 = time.perf_counter()
if {cpu!r}:
    import jax
    import jax._src.xla_bridge as xb
    jax.config.update("jax_platforms", "cpu")
    for name in list(xb._backend_factories):
        if name != "cpu":
            xb._backend_factories.pop(name, None)
import jax
jax.devices()  # force backend/tunnel init so it lands in init_s, not load
sys.path.insert(0, {bench_dir!r})
import bench
from tpu_ir.search import Scorer  # library imports are process cost too
init_s = time.perf_counter() - t0
# transport fingerprint taken INSIDE this process, moments before the
# load: the tunnel state the load actually experiences, not the parent's
probe = bench.transport_probe()
t1 = time.perf_counter()
s = Scorer.load({index_dir!r}, layout="auto")
jax.block_until_ready(bench.serving_arrays(s))
index_s = time.perf_counter() - t1
print("WARM_JSON=" + json.dumps({{
    "load_s": round(init_s + index_s, 2),
    "init_s": round(init_s, 2),
    "index_s": round(index_s, 2),
    **bench.load_stage_breakdown(),
    **bench.profile_breakdown(),
    **probe,
}}))
"""


def load_stage_breakdown() -> dict:
    """The load.* stage seconds (verify / read / assemble / h2d) plus
    effective H2D bandwidth from this process's telemetry registry —
    recorded in every BENCH row and BENCH_HISTORY.jsonl so the
    cold-start trajectory is tracked like throughput (ISSUE 5). Stages
    that never fired report 0.0; keys are flat (load_verify_s, ...,
    load_h2d_mbps) so history rows stay grep/jq-friendly."""
    from tpu_ir.obs import LOAD_STAGES, get_registry

    snap = get_registry().snapshot()
    hists = snap.get("histograms", {})
    out = {}
    for stage in LOAD_STAGES:
        s = hists.get(stage, {})
        out[stage.replace(".", "_") + "_s"] = round(
            s.get("sum_ms", 0.0) / 1e3, 3)
    h2d_bytes = snap.get("counters", {}).get("load.h2d_bytes", 0)
    out["load_h2d_bytes"] = int(h2d_bytes)
    h2d_s = out["load_h2d_s"]
    out["load_h2d_mbps"] = (round(h2d_bytes / (1 << 20) / h2d_s, 1)
                            if h2d_s > 0 and h2d_bytes else -1.0)
    return out


# keys profile_breakdown emits; the warm child's copies ride into the
# BENCH row warm_-prefixed (like the load_* stage split)
PROFILE_KEYS = ("compile_s", "recompiles", "device_time_ms",
                "peak_hbm_bytes")


def profile_breakdown() -> dict:
    """The device-cost profiling fields of a BENCH row (ISSUE 7), from
    this process's registry: total XLA compile seconds (`compile.time`
    sum), recompile count (same-signature compiles — the micro-batching
    ladder's classic silent failure), per-dispatch device time
    (`dispatch.device` p50, the pure compute+wait slice split out of
    the host-measured `device_rtt_ms`), and peak HBM bytes (the
    `device.peak_bytes` gauge; -1 on hosts whose backend reports no
    memory_stats, e.g. CPU)."""
    from tpu_ir.obs import get_registry

    snap = get_registry().snapshot()
    hists = snap.get("histograms", {})
    comp = hists.get("compile.time", {})
    dd = hists.get("dispatch.device", {})
    peak = int(snap.get("gauges", {}).get("device.peak_bytes", 0))
    return {
        "compile_s": round((comp.get("sum_ms") or 0.0) / 1e3, 3),
        "recompiles": int(snap.get("counters", {}).get(
            "compile.recompiles", 0)),
        "device_time_ms": (round(dd["p50_ms"], 3)
                           if dd.get("count") and dd.get("p50_ms")
                           is not None else -1.0),
        "peak_hbm_bytes": peak if peak > 0 else -1,
    }


def _warm_load_subprocess(index_dir: str, cpu: bool,
                          attempts: int = 2) -> dict:
    """Time Scorer.load in fresh interpreters (true process restarts).

    Splits the PROCESS-fixed cost (python + jax import + backend/tunnel
    init — paid by any jax program, index or not) from the index-load
    cost proper, so a large fixed cost cannot masquerade as a slow load
    (VERDICT r2 weak #2). Hardened per VERDICT r4 next #2: every child
    runs the transport probe ITSELF right before loading and reports it
    alongside its timings; the parent takes best-of-N and records every
    run — so a slow warm number is attributable to the tunnel (or not)
    from the artifact alone. Values are -1.0 if every child fails."""
    import subprocess

    bench_dir = os.path.dirname(os.path.abspath(__file__))
    runs = []
    for _ in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 _WARM_LOAD_CODE.format(cpu=cpu, index_dir=index_dir,
                                        bench_dir=bench_dir)],
                capture_output=True, text=True, timeout=3600)
            for line in r.stdout.splitlines():
                if line.startswith("WARM_JSON="):
                    runs.append(json.loads(line.split("=", 1)[1]))
                    break
        except (subprocess.SubprocessError, OSError, ValueError):
            continue
    if not runs:
        return {"scorer_load_warm_s": -1.0, "warm_process_fixed_s": -1.0,
                "warm_index_load_s": -1.0, "warm_runs": []}
    best = min(runs, key=lambda m: m["index_s"])
    return {
        # headline = the best run's numbers (steady-state warm load);
        # warm_runs carries every attempt with its own transport probe
        "scorer_load_warm_s": best["load_s"],
        "warm_process_fixed_s": best["init_s"],
        "warm_index_load_s": best["index_s"],
        "warm_h2d_mbps": best.get("h2d_mbps", -1.0),
        "warm_device_rtt_ms": best.get("device_rtt_ms", -1.0),
        # the child's own load.* stage split and profiling fields
        # (compile seconds / recompiles / peak HBM of a true process
        # restart), warm_-prefixed so the row carries both cold
        # (parent) and warm (child) breakdowns; the child's total
        # load_s is excluded — it already lands above as
        # scorer_load_warm_s, and a warm_load_s twin would double-count
        # the total into the warm_load_* stage keys for any consumer
        # summing them
        **{f"warm_{k}": v for k, v in best.items()
           if (k.startswith("load_") or k in PROFILE_KEYS)
           and k != "load_s"},
        "warm_runs": runs,
    }


def transport_probe() -> dict:
    """Transport fingerprint: H2D / D2H bandwidth on a 32 MB buffer plus
    the scalar-fetch round trip (p50 of 20). These are the numbers that
    move when the tunnel has a bad day — recording them in the bench JSON
    makes a throughput swing attributable from the artifact alone
    (VERDICT r2 weak #1: the round-2 record halved with no way to tell a
    tunnel day from a code regression)."""
    import jax
    import jax.numpy as jnp

    mb = 32
    buf = np.random.default_rng(0).integers(
        0, 255, mb << 20, dtype=np.uint8)

    # scalar round trip first (feeds the h2d estimate). A FRESH scalar
    # each rep: jax.Array caches its fetched numpy value, so re-fetching
    # one array times a dict hit, not the wire.
    base = jnp.zeros((), jnp.int32)
    jax.block_until_ready(base)
    rtts = []
    for i in range(20):
        y = base + i
        jax.block_until_ready(y)
        t0 = time.perf_counter()
        np.asarray(y)
        rtts.append(time.perf_counter() - t0)
    rtt_s = float(np.percentile(rtts, 50))

    # H2D: device_put alone can complete asynchronously on plugin
    # backends (block_until_ready has no transfer to wait on), so force
    # the bytes across with a dependent reduce + scalar fetch and
    # subtract the round trip
    d = jax.device_put(buf)
    s = jnp.sum(d, dtype=jnp.uint32)
    np.asarray(s)                     # warm transfer path + compile
    del d, s
    t0 = time.perf_counter()
    d = jax.device_put(buf)
    s = jnp.sum(d, dtype=jnp.uint32)
    np.asarray(s)
    h2d_s = max(time.perf_counter() - t0 - rtt_s, 1e-9)

    t0 = time.perf_counter()
    np.asarray(d)                     # full-buffer D2H (uncached array)
    d2h_s = time.perf_counter() - t0
    return {
        "h2d_mbps": round(mb / h2d_s, 1),
        "d2h_mbps": round(mb / d2h_s, 1),
        "device_rtt_ms": round(rtt_s * 1e3, 2),
    }


def device_build_control(corpus: str, reps: int = 3) -> dict:
    """Transport-INDEPENDENT build control: the exact device program the
    builder runs (same prep, same shapes, same data), timed with
    block_until_ready and NO result fetch — pure dispatch + device
    compute. If docs/s drops across rounds while this number holds, the
    loss is transport/host, not the device pipeline; if this moves, the
    code regressed. Also reports the host tokenize time separately."""
    import jax
    import jax.numpy as jnp

    from tpu_ir.analysis.native import tokenize_corpus_native
    from tpu_ir.ops import PAD_TERM, PAD_TERM_U16, build_postings_packed_jit

    t0 = time.perf_counter()
    docids, temp_ids, lengths, vocab_list = tokenize_corpus_native([corpus])
    tokenize_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    vocab_arr = np.array(vocab_list, dtype=np.str_)
    order = np.argsort(vocab_arr)
    rank = np.empty(len(order), np.int64)
    rank[order] = np.arange(len(order))
    flat_term_ids = rank[temp_ids].astype(np.int32)
    docnos = (np.argsort(np.argsort(np.array(docids, dtype=np.str_)))
              + 1).astype(np.int32)
    v = len(vocab_list)
    occurrences = len(flat_term_ids)
    granule = 1 << 18
    cap = max(granule, (occurrences + granule - 1) // granule * granule)
    use16 = v < int(PAD_TERM_U16)
    term_ids = np.full(cap, PAD_TERM_U16 if use16 else PAD_TERM,
                       np.uint16 if use16 else np.int32)
    term_ids[:occurrences] = flat_term_ids
    host_prep_s = time.perf_counter() - t0

    t_dev, l_dev = jnp.asarray(term_ids), jnp.asarray(
        lengths.astype(np.int32))
    d_dev = jnp.asarray(docnos)
    times = []
    for _ in range(reps + 1):  # first rep includes compile; dropped
        t0 = time.perf_counter()
        p = build_postings_packed_jit(t_dev, d_dev, l_dev, vocab_size=v,
                                      num_docs=len(docids))
        jax.block_until_ready((p.pair_doc, p.pair_tf, p.df))
        times.append(time.perf_counter() - t0)
    return {
        "control_tokenize_s": round(tokenize_s, 3),
        "control_host_prep_s": round(host_prep_s, 3),
        "control_device_build_s": round(min(times[1:]), 3),
        "control_device_build_runs": [round(t, 3) for t in times[1:]],
    }


def device_query_control(scorer, q_ids: np.ndarray, reps: int = 3) -> dict:
    """Transport-INDEPENDENT query control with a MaxScore A/B: one query
    block dispatched with block_until_ready and NO result fetch, timed
    with the static cold-only kernel (skip_hot — what the scheduler
    dispatches for hot-free blocks) and with the full kernel. The delta
    is the measured device-side value of the pruning (VERDICT r4 next
    #1); engagement fractions say how many blocks of this query load
    take the skip kernel. Tiered (sparse) layouts only."""
    if scorer.layout != "sparse":
        return {"control_query_layout": scorer.layout}
    import jax

    block = scorer._block_size()
    q_all = np.asarray(q_ids, np.int32)
    # measure a hot-free prefix in dispatch order: skip_hot is only
    # exact (and only ever dispatched) for such blocks. Padded back to
    # `block` rows with PAD queries so the compiled shape matches real
    # dispatches.
    has_hot, n_free, mode = scorer._skip_plan(q_all)
    sched = q_all[scorer._schedule_order(has_hot)]
    out = dict(scorer.prune_diag(q_all))
    out["control_query_block"] = block
    out["control_query_block_hot_free"] = min(block, n_free)
    if mode == "all_full":
        # topk() never dispatches the skip kernel for this load (no
        # hot-free queries, or fewer than MIN_SKIP_GROUP — the shared
        # _skip_plan is the authority), so an A/B here would fabricate
        # a speedup that never materializes
        out["control_query_skip_na"] = True
        return out
    q = np.full((block, q_all.shape[1]), -1, np.int32)
    q[: min(block, n_free)] = sched[: min(block, n_free)]
    for skip, key in ((True, "control_device_query_s"),
                      (False, "control_device_query_noprune_s")):
        times = []
        for _ in range(reps + 1):  # first rep includes compile; dropped
            t0 = time.perf_counter()
            s, d = scorer._topk_device(q, 10, "tfidf", skip_hot=skip)
            jax.block_until_ready((s, d))
            times.append(time.perf_counter() - t0)
        out[key] = round(min(times[1:]), 4)
        out[key + "_runs"] = [round(t, 4) for t in times[1:]]
    return out


def v48_extrapolation(controls: dict, phases: dict, num_docs: int,
                      n_queries: int = 10_000) -> dict:
    """North-star extrapolation computed IN the artifact (VERDICT r4
    next #4): what the <60 s / 1M-doc target looks like on a v4-8
    (4 chips, no tunnel), from THIS run's own measurements.

    - device build: the per-chip ceiling measured by the ref-scale probe
      control (`control_device_build_s`, block_until_ready, no fetch),
      scaled by 4 chips — the build's device program is
      throughput-parallel over doc shards (parallel/sharded_build.py).
    - host phases: taken AS MEASURED on this 1-core container
      (conservative: a real v4-8 host has ~120 cores and the C++
      scanner shards trivially by file chunk).
    - queries: the device-only query control per block, scaled to the
      10k batch over 4 doc-sharded chips (parallel/sharded_tiered.py).

    Every input rides in the same JSON, so the estimate is recomputable
    from the artifact alone."""
    if "control_device_build_s" not in controls:
        return {}
    chip_rate = DOC_COUNT_REF / controls["control_device_build_s"]
    dev_s = num_docs / (chip_rate * 4)
    host_s = sum(v for k, v in phases.items()
                 if k.startswith("phase_") and k != "phase_pass2_combine_s"
                 and isinstance(v, (int, float)))
    out = {
        "v48_chip_docs_per_sec": round(chip_rate, 1),
        "v48_device_build_s_est": round(dev_s, 1),
        "v48_host_phases_s_measured": round(host_s, 1),
        "v48_build_s_est": round(dev_s + host_s, 1),
    }
    q_s, blk = (controls.get("control_device_query_s"),
                controls.get("control_query_block"))
    if q_s and blk:
        out["v48_query_10k_s_est"] = round(
            q_s * (n_queries / blk) / 4, 2)
        out["v48_north_star_s_est"] = round(
            out["v48_build_s_est"] + out["v48_query_10k_s_est"], 1)
    return out


DOC_COUNT_REF = 8_761  # the probe-corpus size the chip ceiling is measured on


def _append_history(out: dict) -> None:
    """Append this run's summary row to the cumulative
    BENCH_HISTORY.jsonl next to this script (timestamp- and
    commit-sha-stamped), so the perf trajectory across PRs is one
    machine-readable file instead of scattered BENCH_*.json snapshots.
    Best-effort: a read-only checkout must not fail the bench. ONE
    stamping/writing implementation, shared with the serve-bench sweep
    (obs/bench_check.append_history_row) so the row schema cannot
    diverge."""
    from tpu_ir.obs.bench_check import append_history_row

    here = os.path.dirname(os.path.abspath(__file__))
    append_history_row(out, path=os.path.join(here, "BENCH_HISTORY.jsonl"))


def _build_phase_timings(index_dir: str) -> dict:
    """Surface the builder's own JobReport phase timings into the bench
    JSON (they were always recorded, never published — VERDICT r2 next #1)."""
    import glob

    for path in glob.glob(os.path.join(index_dir, "jobs",
                                       "TermKGramDocIndexer*.json")):
        with open(path) as f:
            rep = json.load(f)
        return {f"phase_{k}_s": v for k, v in sorted(
            rep.get("timings_s", {}).items())}
    return {}


def _cpu_control_subprocess(timeout_s: int = 900) -> dict:
    """Run the build-only bench on the CPU backend in a subprocess: a
    transport-free, device-free control of the SAME code path. Stable
    across tunnel days; moves only when the code does."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--cpu",
             "--build-only"],
            capture_output=True, text=True, timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        for line in reversed(r.stdout.splitlines()):
            if line.startswith("{"):
                child = json.loads(line)
                return {
                    "cpu_control_docs_per_sec": child.get("value", -1.0),
                    "cpu_control_index_wall_s": child.get(
                        "index_wall_s", -1.0),
                }
    except (subprocess.SubprocessError, OSError, ValueError):
        pass
    return {"cpu_control_docs_per_sec": -1.0,
            "cpu_control_index_wall_s": -1.0}


def _tpu_probe_ok(timeout_s: int = 120) -> bool:
    """True if the accelerator backend initializes within the timeout.

    The TPU tunnel in this environment can wedge so that jax.devices()
    blocks forever (NOTES.md); probing in a subprocess keeps the bench from
    hanging and lets it fall back to the CPU backend with a number instead
    of no output at all."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "raise SystemExit(0 if d else 1)"],
            timeout=timeout_s, capture_output=True)
        return r.returncode == 0
    except (subprocess.SubprocessError, OSError):
        return False


def pass_metrics(phases: dict, build_s: float) -> dict:
    """Sentry-gated per-phase aliases for a streaming build row: the
    curated METRICS names (build_s / pass1_tokenize_s / pass2_combine_s
    / pass3_reduce_s, direction-aware lower-is-better in
    obs/bench_check.py) lifted out of the phase_* decomposition so the
    regression sentry gates build performance from this PR on."""
    out = {"build_s": round(build_s, 2)}
    for phase in ("pass1_tokenize", "pass2_combine", "pass3_reduce"):
        v = phases.get(f"phase_{phase}_s")
        if isinstance(v, (int, float)):
            out[f"{phase}_s"] = round(v, 2)
    return out


def run_scaling(args, backend: str) -> int:
    """`--scaling N,N,...`: per-phase build scaling sweep (ISSUE 11).

    For each docs count, synthesizes a proportional corpus (~2.7 KB/doc,
    the wiki configs' shape), runs the streaming radix build, and
    records one build_scale-<docs>d row per count — pass1/pass2/pass3
    wall seconds, corpus + spill bytes, pairs — in BENCH_HISTORY.jsonl.
    Linear build scaling is the claim; these rows are the evidence (and
    the bench-check comparability groups that gate it)."""
    from tpu_ir.index.streaming import build_index_streaming
    from tpu_ir.obs import get_registry

    counts = [int(x) for x in args.scaling.split(",") if x]
    radix = args.radix_buckets if args.radix_buckets is not None else 16
    rows = []
    for n_docs in counts:
        with tempfile.TemporaryDirectory() as tmp:
            corpus = os.path.join(tmp, "corpus.trec")
            nbytes = make_corpus(
                corpus, n_docs=n_docs, target_bytes=n_docs * 2_700,
                vocab_size=max(30_000, n_docs // 2))
            index_dir = os.path.join(tmp, "index")
            get_registry().snapshot(reset=True)
            t0 = time.perf_counter()
            build_index_streaming(
                [corpus], index_dir, k=1, num_shards=10,
                compute_chargrams=False, radix_buckets=radix,
                tokenize_procs=args.tokenize_procs)
            build_s = time.perf_counter() - t0
            phases = _build_phase_timings(index_dir)
            snap = get_registry().snapshot()
            # the comparability key carries the BUILD SHAPE (bucket
            # count, pool size) like serve_sweep-<docs>d-c<top> does:
            # bench-check groups rows by config, and a radix run judged
            # against a legacy-row median would breach (or mask) on the
            # mode difference, not a regression
            shape = f"-r{radix}" + (
                f"-p{args.tokenize_procs}" if args.tokenize_procs else "")
            row = {
                "metric": "build_scale",
                "config": f"build_scale-{n_docs}d{shape}",
                "backend": backend,
                "build_only": True,
                "num_docs": n_docs,
                "radix_buckets": radix,
                "tokenize_procs": args.tokenize_procs or 1,
                "corpus_bytes": nbytes,
                "spill_bytes": snap["counters"].get(
                    "build.radix.spill_bytes", 0),
                "docs_per_sec": round(n_docs / build_s, 1),
                **pass_metrics(phases, build_s),
                **phases,
            }
            rows.append(row)
            _append_history(row)
            print(json.dumps(row))
    return 0


def _postings_bytes(index_dir: str) -> tuple[int, int]:
    """(postings part bytes, whole index-dir bytes). The part files are
    the compressible payload the ratio is judged on; the dir total says
    what a worker actually rsyncs."""
    from tpu_ir.index import format as fmt

    meta = fmt.IndexMetadata.load(index_dir)
    parts = sum(os.path.getsize(fmt.part_path(index_dir, s))
                for s in range(meta.num_shards))
    total = 0
    for root, _dirs, files in os.walk(index_dir):
        total += sum(os.path.getsize(os.path.join(root, f)) for f in files)
    return parts, total


def _measure_compress_variant(index_dir: str, n_queries: int,
                              cpu: bool) -> tuple[dict, tuple]:
    """One side of the --compress A/B: cold load with the load.* stage
    split (serving cache removed first — the point is the from-disk
    path), a true-restart warm load, and the batched BM25 top-10 rate
    with block-max pruning on and off. Returns (row fields, a 64-query
    (scores, docnos) parity sample taken with pruning on)."""
    import jax

    from tpu_ir.index import format as fmt
    from tpu_ir.obs import get_registry
    from tpu_ir.search import Scorer

    meta = fmt.IndexMetadata.load(index_dir)
    parts, total = _postings_bytes(index_dir)
    out = {
        "compressed": bool(getattr(meta, "compressed", False)),
        "tf_dtype": getattr(meta, "tf_dtype", "int32"),
        "tf_lossy": bool(getattr(meta, "tf_lossy", False)),
        "index_bytes": parts,
        "index_dir_bytes": total,
        "bytes_per_doc": round(parts / meta.num_docs, 2),
    }
    shutil.rmtree(os.path.join(index_dir, "serving-tiered"),
                  ignore_errors=True)
    get_registry().snapshot(reset=True)
    # arm the format layer's streamed-bytes meter: on a page-cached CPU
    # container load_read_s barely moves (decode replaces disk wait), so
    # the "reads shrink with the payload" claim is made on BYTES — the
    # quantity that survives to machines where reads cost real time
    fmt.reset_read_bytes()
    t0 = time.perf_counter()
    scorer = Scorer.load(index_dir, layout="auto")
    jax.block_until_ready(serving_arrays(scorer))
    out["scorer_load_cold_s"] = round(time.perf_counter() - t0, 2)
    out["cold_read_bytes"] = int(sum(
        fmt.read_bytes_streamed().values()))
    fmt.reset_read_bytes(arm=False)
    out.update(load_stage_breakdown())
    out.update(_warm_load_subprocess(index_dir, cpu=cpu, attempts=1))
    out.pop("warm_runs", None)

    rng = np.random.default_rng(1)
    q_ids = rng.integers(0, meta.vocab_size, size=(n_queries, 2)).astype(
        np.int32)
    parity = None
    for bm, tag in (("1", "topk_qps_blockmax_on"),
                    ("0", "topk_qps_blockmax_off")):
        os.environ["TPU_IR_BLOCKMAX"] = bm
        try:
            scorer.topk(q_ids, k=10, scoring="bm25")  # compile
            t0 = time.perf_counter()
            scores, docnos = scorer.topk(q_ids, k=10, scoring="bm25")
            out[tag] = round(n_queries / (time.perf_counter() - t0), 1)
            if bm == "1":
                parity = (np.asarray(scores[:64]), np.asarray(docnos[:64]))
        finally:
            os.environ.pop("TPU_IR_BLOCKMAX", None)
    out["query_batch"] = n_queries
    out["layout"] = scorer.layout
    # decode/compress telemetry for this variant's loads + dispatches
    # (zero on the raw side — the counters existing at 0 is the signal
    # that the fused path never engaged)
    for name, v in get_registry().snapshot()["counters"].items():
        if name.startswith(("decode.", "compress.")):
            out[name.replace(".", "_")] = int(v)
    return out, parity


def run_compress_ab(args, backend: str, streaming: bool) -> int:
    """`--compress`: the ISSUE 20 A/B. Build ONE index at the config's
    scale, measure it raw, migrate a copy to the compressed arena
    (tpu-ir migrate-index --compress equivalent), measure that, and
    append BOTH rows to BENCH_HISTORY.jsonl under per-variant configs
    (compress_ab-<docs>d-raw / -compressed) so the bench-check sentry
    gates index_bytes / bytes_per_doc / load_read_s / load_h2d_s per
    variant. In-process acceptance: the postings payload must shrink
    >= 2.5x, and lossless modes must serve the same top-10 (scores
    compared as float32 BITS) as the raw index."""
    from tpu_ir.index import format as fmt
    from tpu_ir.index.migrate import migrate_index

    n_queries = min(args.queries or 2_000, 2_000)
    with tempfile.TemporaryDirectory() as tmp:
        corpus = os.path.join(tmp, "corpus.trec")
        make_corpus(corpus)
        raw_dir = os.path.join(tmp, "index-raw")
        t0 = time.perf_counter()
        if streaming:
            from tpu_ir.index.streaming import build_index_streaming

            radix = (args.radix_buckets if args.radix_buckets is not None
                     else 16)
            build_index_streaming([corpus], raw_dir, k=1, chargram_ks=[],
                                  num_shards=10, radix_buckets=radix,
                                  tokenize_procs=args.tokenize_procs)
        else:
            from tpu_ir.index import build_index

            build_index([corpus], raw_dir, k=1, chargram_ks=[],
                        num_shards=10, compute_chargrams=False)
        build_s = time.perf_counter() - t0

        raw_row, raw_parity = _measure_compress_variant(
            raw_dir, n_queries, args.cpu)

        comp_dir = os.path.join(tmp, "index-comp")
        shutil.copytree(raw_dir, comp_dir)
        shutil.rmtree(os.path.join(comp_dir, "serving-tiered"),
                      ignore_errors=True)
        t0 = time.perf_counter()
        migrate_index(comp_dir, to_version=fmt.COMPRESSED_FORMAT_VERSION,
                      tf_dtype=args.tf_dtype)
        migrate_s = time.perf_counter() - t0
        comp_row, comp_parity = _measure_compress_variant(
            comp_dir, n_queries, args.cpu)

        ratio = round(raw_row["index_bytes"]
                      / max(comp_row["index_bytes"], 1), 2)
        if comp_row["tf_lossy"]:
            parity = "skipped (lossy int8)"
        else:
            s_r, d_r = raw_parity
            s_c, d_c = comp_parity
            bad = int((d_r != d_c).any(axis=1).sum()
                      + (s_r.astype(np.float32).view(np.uint32)
                         != s_c.astype(np.float32).view(np.uint32))
                      .any(axis=1).sum())
            parity = "ok" if bad == 0 else f"{bad} queries differ"
        common = {
            "metric": "compress_ab",
            "backend": backend,
            "num_docs": DOC_COUNT,
            "build_s": round(build_s, 2),
            "compress_ratio": ratio,
            "serving_parity": parity,
        }
        raw_row = {**common,
                   "config": f"compress_ab-{DOC_COUNT}d-raw", **raw_row}
        comp_row = {**common,
                    "config": f"compress_ab-{DOC_COUNT}d-compressed",
                    "migrate_s": round(migrate_s, 2),
                    "raw_index_bytes": raw_row["index_bytes"], **comp_row}
        for row in (raw_row, comp_row):
            _append_history(row)
            print(json.dumps(row))
    bad = []
    if ratio < 2.5:
        bad.append(f"compression ratio {ratio} below the 2.5x floor")
    if (comp_row["cold_read_bytes"] * 2.0
            > raw_row["cold_read_bytes"]):
        bad.append(
            f"cold-load bytes read did not drop with the payload: "
            f"{comp_row['cold_read_bytes']} vs raw "
            f"{raw_row['cold_read_bytes']}")
    if parity not in ("ok", "skipped (lossy int8)"):
        bad.append(f"raw-vs-compressed serving parity broke: {parity}")
    if bad:
        print("bench --compress FAILED: " + "; ".join(bad),
              file=sys.stderr)
        return 1
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="force CPU backend (local-mode equivalent)")
    ap.add_argument("--queries", type=int, default=None,
                    help="query-batch size (default: 10000; msmarco: 2000)")
    ap.add_argument("--build-only", action="store_true",
                    help="corpus + warmup + timed builds only (used as the "
                         "CPU control subprocess; skips serving/query/"
                         "control measurements)")
    ap.add_argument("--no-controls", action="store_true",
                    help="skip the transport probe, device-only build "
                         "control, and CPU control subprocess")
    ap.add_argument("--compress", action="store_true",
                    help="compressed-arena A/B (ISSUE 20): build one "
                         "index at the config's scale, measure raw, "
                         "migrate a copy to the compressed arena, "
                         "measure again, and append a raw/compressed "
                         "row PAIR (index_bytes, bytes_per_doc, "
                         "cold/warm load stage split, BM25 top-10 QPS "
                         "with block-max on/off) to BENCH_HISTORY.jsonl; "
                         "fails unless the postings shrink >= 2.5x and "
                         "lossless modes serve bit-identical top-10")
    ap.add_argument("--tf-dtype", choices=["int8", "bf16"], default=None,
                    help="tf quantization for --compress (default: auto "
                         "= int8 when lossless for this index, else "
                         "bf16)")
    ap.add_argument("--scaling", default=None, metavar="DOCS[,DOCS...]",
                    help="per-phase build scaling sweep: for each docs "
                         "count, synthesize a proportional corpus, run "
                         "the streaming radix build, and append a "
                         "build_scale-<docs>d row (pass1/pass2/pass3 "
                         "wall + bytes) to BENCH_HISTORY.jsonl — the "
                         "rows the bench-check sentry gates build perf "
                         "on; skips all query/serving measurement")
    ap.add_argument("--radix-buckets", type=int, default=None,
                    help="radix buckets for streaming builds (default: "
                         "16 for streaming configs and the scaling "
                         "sweep; 0 = legacy per-batch pass 2)")
    ap.add_argument("--tokenize-procs", type=int, default=None,
                    help="tokenizer pool size for the pure-Python "
                         "analyzer path (default: env/1)")
    ap.add_argument("--config",
                    choices=["ref", "wiki100k", "wiki1m", "msmarco"],
                    default="ref",
                    help="ref = reference-scale corpus (8,761 docs / 23 MB); "
                         "wiki100k = 100k docs / ~270 MB, streaming build; "
                         "wiki1m = 1M docs / ~2.7 GB, streaming build (no "
                         "warm-up run — relies on the persistent compile "
                         "cache, so the first-ever run includes compiles); "
                         "msmarco = 50k passages + 2k planted-relevance "
                         "queries, BM25 MRR@10 + top-1000 candidates")
    args = ap.parse_args()
    if args.queries is None and args.config != "msmarco":
        args.queries = 10_000

    global DOC_COUNT, TARGET_BYTES, VOCAB_SIZE
    streaming = False
    if args.config == "wiki100k":
        DOC_COUNT, TARGET_BYTES, VOCAB_SIZE = 100_000, 270_000_000, 200_000
        streaming = True
    elif args.config == "wiki1m":
        DOC_COUNT, TARGET_BYTES, VOCAB_SIZE = (
            1_000_000, 2_700_000_000, 500_000)
        streaming = True

    if not args.cpu and not _tpu_probe_ok():
        print("bench: TPU backend probe failed/timed out; falling back "
              "to CPU", file=sys.stderr)
        args.cpu = True
    if args.cpu:
        import jax
        import jax._src.xla_bridge as xb

        jax.config.update("jax_platforms", "cpu")
        for name in list(xb._backend_factories):
            if name != "cpu":
                xb._backend_factories.pop(name, None)
    import jax

    backend = jax.devices()[0].platform

    if args.scaling:
        return run_scaling(args, backend)

    if args.compress:
        return run_compress_ab(args, backend, streaming)

    if args.config == "msmarco":
        out = run_msmarco(args)
        out["backend"] = backend
        _append_history(out)
        print(json.dumps(out))
        if out["quality_gate_enforced"] and out["quality_gate"] != "ok":
            return 1
        # the eval loop is a deterministic correctness assertion (same
        # index, same queries, same scorer) — any mismatch fails
        if out.get("eval_loop") != "ok":
            return 1
        # MaxScore pruning must be rank-safe on the gate corpus
        if out.get("prune_parity", "ok") != "ok":
            return 1
        # the real-corpus eval must actually RUN: its floors live in
        # quality_gate but only apply when real_eval == "ok", so an
        # end-to-end breakage of stdlib indexing/search must fail here
        # rather than silently skipping the gate
        if out.get("real_eval") != "ok":
            print(f"bench: real-corpus eval failed: "
                  f"{out.get('real_eval')}", file=sys.stderr)
            return 1
        return 0

    from tpu_ir.index import build_index
    from tpu_ir.search import Scorer

    with tempfile.TemporaryDirectory() as tmp:
        corpus = os.path.join(tmp, "corpus.trec")
        nbytes = make_corpus(corpus)
        index_dir = os.path.join(tmp, "index")

        # warm-up build: compiles/loads every device program at the exact
        # shapes of the timed build (same corpus -> same static shapes),
        # so the timed runs measure steady-state throughput, not XLA
        # compilation or executable-cache deserialization. The TPU sits
        # behind a network tunnel whose round-trip latency is noisy, so the
        # timed build repeats and the fastest run is the headline number
        # (all runs are recorded).
        if streaming:
            from tpu_ir.index.streaming import build_index_streaming

            radix = (args.radix_buckets if args.radix_buckets is not None
                     else 16)

            # store=True: the docstore rides pass 1's text spills (zero
            # extra corpus reads — VERDICT r4 next #5); its cost shows up
            # attributed as phase_docstore_s + the pass-1 spill overhead.
            # Streaming configs default to the radix-partitioned pass 2
            # (ISSUE 11) — bit-identical artifacts, so the row stays
            # comparable to its pre-radix history.
            def one_build(out):
                build_index_streaming([corpus], out, k=1,
                                      chargram_ks=[2, 3], num_shards=10,
                                      store=True, radix_buckets=radix,
                                      tokenize_procs=args.tokenize_procs)
        else:
            def one_build(out):
                build_index([corpus], out, k=1, chargram_ks=[2, 3],
                            num_shards=10)

        if args.config != "wiki1m":  # 1M-doc warm-up would double a long run
            warm_dir = os.path.join(tmp, "index-warmup")
            one_build(warm_dir)
            shutil.rmtree(warm_dir)
        runs = []
        phase_sets = []
        # best-of-N: the tunnel's noise floor moves by whole seconds day to
        # day; five ref-scale builds cost ~20 s total and give the minimum
        # a fair shot at the steady-state number
        n_runs = 1 if streaming else 5
        for r in range(n_runs):
            out = index_dir if r == n_runs - 1 else os.path.join(
                tmp, f"index-run{r}")
            t0 = time.perf_counter()
            one_build(out)
            runs.append(time.perf_counter() - t0)
            # phases are captured per run so the published decomposition
            # belongs to the SAME run as the headline min — the last run
            # can catch a tunnel hiccup and its phases would then sum to
            # more than index_wall_s
            phase_sets.append(_build_phase_timings(out))
            if out != index_dir:
                shutil.rmtree(out)
        build_s = min(runs)
        docs_per_sec = DOC_COUNT / build_s
        phases = phase_sets[runs.index(build_s)]

        # docstore accounting (VERDICT r4 next #5): streaming configs
        # built the store inside the timed build (phase_docstore_s above
        # attributes it); the ref config times the standalone corpus pass
        # the in-memory build uses
        from tpu_ir.index import docstore as ds

        if not streaming and not args.build_only:
            t0 = time.perf_counter()
            ds.build_docstore([corpus], index_dir)
            phases["docstore_build_s"] = round(time.perf_counter() - t0, 3)
        if ds.available(index_dir):
            st = ds.stats(index_dir)
            phases["docstore_raw_bytes"] = st["raw_bytes"]
            phases["docstore_stored_bytes"] = st["stored_bytes"]

        if args.build_only:
            out = {
                "metric": "docs_per_sec_indexed",
                "value": round(docs_per_sec, 1),
                "unit": "docs/s",
                "vs_baseline": round(docs_per_sec / BASELINE_DOCS_PER_SEC,
                                     2),
                "index_wall_s": round(build_s, 2),
                "index_wall_s_runs": [round(r, 2) for r in runs],
                "backend": backend,
                "config": args.config,
                "build_only": True,
                **phases,
                **profile_breakdown(),
            }
            _append_history(out)
            print(json.dumps(out))
            return 0

        # self-attribution controls (VERDICT r2 next #1): transport
        # fingerprint + transport-independent device-only build + a
        # CPU-backend build of the same code — together they say whether
        # a cross-round throughput swing is tunnel weather or a regression
        controls: dict = {}
        if not args.no_controls:
            try:
                controls.update(transport_probe())
                # the whole-corpus single-program control only matches the
                # in-memory builder's real shape at ref scale; at wiki
                # scale it would dispatch one ~200M-element program the
                # streaming builder never runs (and big enough to wedge
                # the tunnel — observed UNAVAILABLE at 1M docs)
                if args.config == "ref":
                    controls.update(device_build_control(corpus))
                    if not args.cpu:
                        controls.update(_cpu_control_subprocess())
                elif streaming:
                    # wiki scale: measure the per-chip device ceiling on
                    # a ref-scale PROBE corpus (the whole-corpus single
                    # program at 1M would wedge the tunnel — observed
                    # UNAVAILABLE) and extrapolate from it (see
                    # v48_extrapolation below)
                    probe = os.path.join(tmp, "probe.trec")
                    make_corpus(probe, n_docs=DOC_COUNT_REF,
                                target_bytes=23_950_858, vocab_size=30_000)
                    controls.update(device_build_control(probe))
            except Exception as e:  # noqa: BLE001 — controls are evidence,
                controls["controls_error"] = str(e)[:300]  # not the metric

        # post-build verification gate (VERDICT r1 item 5): the vectorized
        # structural check must hold — and stay fast — at every bench scale
        from tpu_ir.index.verify import verify_index

        t0 = time.perf_counter()
        verify_index(index_dir)  # AssertionError fails the bench loudly
        verify_s = time.perf_counter() - t0

        # cold load: builds the serving-tiered disk cache (tiered corpora);
        # warm load: a REAL process restart against the populated cache —
        # the steady-state serving cold start (VERDICT r1 item 3's metric),
        # including jax init. Measuring it in this process would overlay
        # the new scorer's multi-GB uploads on the one already resident.
        def _await_device(s):
            jax.block_until_ready(serving_arrays(s))

        # serving + query measurements: a transient device/tunnel failure
        # here (e.g. UNAVAILABLE after a 40-minute 1M-doc build) must not
        # discard the build record — the timed build is the headline.
        # AssertionError stays fatal (verify/recall correctness gates).
        load_cold_s = query_s = -1.0
        cold_breakdown = {}
        warm = {}
        lat_ms = np.array([-1.0])
        recall = -1.0
        queries_per_sec = -1.0
        serving_error = None
        try:
            t0 = time.perf_counter()
            scorer = Scorer.load(index_dir, layout="auto")
            _await_device(scorer)
            load_cold_s = time.perf_counter() - t0
            # the cold load's own stage split (verify/read/assemble/h2d),
            # snapshotted before anything else can observe load.* —
            # nothing earlier in this process runs a Scorer.load
            cold_breakdown = load_stage_breakdown()
            warm = _warm_load_subprocess(index_dir, cpu=args.cpu)
            # serving-cache accounting (VERDICT r4 next #7): the cold
            # load above built + persisted the full tier layout, so a
            # warm load's floor is uploading these bytes. Recording the
            # cache size next to the warm child's OWN h2d probe makes
            # "warm load ~= upload time" checkable from the artifact:
            # warm_upload_bound_s is that floor at the measured bandwidth.
            cache_dir = os.path.join(index_dir, "serving-tiered")
            if os.path.isdir(cache_dir):
                cache_bytes = sum(
                    os.path.getsize(os.path.join(cache_dir, f))
                    for f in os.listdir(cache_dir))
                warm["serving_cache_bytes"] = cache_bytes
                if warm.get("warm_h2d_mbps", -1) > 0:
                    # the probe reports MiB/s (32 MiB buffer / seconds),
                    # so the floor divides by MiB too
                    warm["warm_upload_bound_s"] = round(
                        cache_bytes / (warm["warm_h2d_mbps"] * (1 << 20)),
                        2)
            rng = np.random.default_rng(1)
            v = scorer.meta.vocab_size
            q_ids = rng.integers(0, v, size=(args.queries, 2)).astype(
                np.int32)

            # compile once at the measured shape, then measure (topk
            # returns host arrays, so completion is synchronous)
            scorer.topk(q_ids, k=10)
            t0 = time.perf_counter()
            scores, docnos = scorer.topk(q_ids, k=10)
            query_s = time.perf_counter() - t0

            # single-query latency (REPL-shaped load): one [1, L] query
            # per topk call, p50/p99 over 50 calls (the reference REPL's
            # per-query cost was dict lookup + disk seek per term;
            # never measured)
            rows = np.stack([q_ids[i % len(q_ids)] for i in range(50)])
            scorer.topk(rows[:1], k=10)  # compile the B=1 shape
            if scorer.layout == "sparse" and scorer.prune:
                # topk selects a B=1 kernel variant per row CONTENT
                # (hot-free -> static skip kernel, hot -> full); warm
                # every class the timed rows will hit so no compile
                # lands inside the loop
                hh = scorer._has_hot(rows)
                for cls in (False, True):
                    idx = np.flatnonzero(hh == cls)
                    if len(idx):
                        scorer.topk(rows[idx[0]][None, :], k=10)
            lat = []
            for row in rows:
                t0 = time.perf_counter()
                scorer.topk(row[None, :], k=10)
                lat.append(time.perf_counter() - t0)
            lat_ms = np.sort(np.array(lat)) * 1e3

            # recall@10 vs an exhaustive numpy oracle on a query sample
            # (BASELINE.json: "recall@10 vs CPU reference")
            sample = {"ref": 64, "wiki1m": 4}.get(args.config, 8)
            recall = _recall_at_10(scorer, q_ids[:sample], docnos[:sample])
            queries_per_sec = args.queries / query_s

            # device-only query control + MaxScore prune A/B (tiered
            # layouts; VERDICT r4 next #1's "measured reduction in the
            # device-only query control")
            if not args.no_controls:
                try:
                    controls.update(device_query_control(scorer, q_ids))
                except Exception as e:  # noqa: BLE001 — evidence only
                    controls["query_control_error"] = str(e)[:300]
                if streaming:
                    controls.update(v48_extrapolation(
                        controls, phases, DOC_COUNT,
                        n_queries=args.queries))
        except AssertionError:
            raise
        except Exception as e:  # noqa: BLE001 — record, don't discard
            serving_error = f"{type(e).__name__}: {e}"
            print(f"bench: serving/query phase failed after a successful "
                  f"build: {serving_error}", file=sys.stderr)

    # per-stage latency breakdown from the unified telemetry layer:
    # span-derived histograms recorded during this process's build and
    # query phases (build.* per pipeline phase, kernel/dispatch per
    # query block) — BENCH_*.json finally carries WHERE time went, not
    # just the headline throughput
    from tpu_ir.obs import get_registry, querylog

    stage_latency = {
        name: {k: s[k] for k in ("count", "p50_ms", "p95_ms", "p99_ms")}
        for name, s in sorted(
            get_registry().snapshot()["histograms"].items())
        if s["count"]}
    # the query-log view of the bench's own query phases: recorded
    # entries and how many tripped the slow-query trap (ISSUE 8) — a
    # bench row that ran with TPU_IR_SLOW_QUERY_MS set shows offenders
    ql = querylog.summary()

    out = {
        "metric": "docs_per_sec_indexed",
        "value": round(docs_per_sec, 1),
        "stage_latency": stage_latency,
        "querylog_recorded": ql["recorded"],
        "slow_queries": ql["slow_trapped"],
        "unit": "docs/s",
        "vs_baseline": round(docs_per_sec / BASELINE_DOCS_PER_SEC, 2),
        "index_wall_s": round(build_s, 2),
        "index_wall_s_runs": [round(r, 2) for r in runs],
        "corpus_bytes": nbytes,
        "corpus_docs": DOC_COUNT,
        "queries_per_sec": round(queries_per_sec, 1),
        "query_batch": args.queries,
        "query_p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
        "query_p99_ms": round(float(np.percentile(lat_ms, 99)), 2),
        "scorer_load_cold_s": round(load_cold_s, 2),
        # cold-load stage split (load.* histograms: verify/read/assemble/
        # h2d seconds + effective h2d MB/s) — the cold-start trajectory
        # is tracked in BENCH_HISTORY like throughput (ISSUE 5)
        **cold_breakdown,
        # warm load split: total = process-fixed (python+jax+tunnel init,
        # paid by ANY jax program) + the index load proper
        **warm,
        "verify_s": round(verify_s, 2),
        "recall_at_10": recall,
        # device-cost profiling (ISSUE 7): whole-process compile wall,
        # recompile count, per-dispatch device time split out of
        # device_rtt_ms, and peak HBM — cold-run side of the pair (the
        # warm_ twins above come from the restart child)
        **profile_breakdown(),
        "backend": backend,
        "config": args.config,
        **(pass_metrics(phases, build_s) if streaming else {}),
        **phases,
        **controls,
    }
    if serving_error is not None:
        out["serving_error"] = serving_error[:300]
    _append_history(out)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
